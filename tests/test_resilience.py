"""Resilience: complete-state checkpointing + fault-tolerant training.

The three contracts from the PR-5 tentpole:

  * atomicity — a writer killed at ANY point before the commit rename
    leaves only an ignored ``.tmp`` staging dir; the previous checkpoint
    stays loadable (crash-mid-save test via ``faultinject.ckpt_crash``);
  * integrity — per-chunk crc32 checksums are verified on load; corrupt
    bytes raise ``CheckpointCorrupt`` naming the offending chunk, and the
    manager falls back to the next older committed checkpoint;
  * bit-identical resume — train 10 steps straight vs. 4 + preemption +
    restore + 6 gives IDENTICAL losses, parameters, RNG chain and LR
    (the checkpoint captures params/opt/scaler/scheduler/RNG/iterator
    cursor completely; the replayed batches are bit-identical).

Plus the satellites: ``wait_async_save`` concurrency + surface-ALL-errors
semantics, transient-write retry with backoff, keep-last-N GC, and the
prefetcher resume cursor (``consumed`` / ``start_offset`` skip-replay).
"""

import json
import os
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.jit as pjit
import paddle_tpu.nn as nn
from paddle_tpu.distributed import checkpoint as dckpt
from paddle_tpu.io import DataLoader, DevicePrefetcher, StackingPrefetcher, \
    TensorDataset
from paddle_tpu.optimizer import lr as lrsched
from paddle_tpu.profiler import counters
from paddle_tpu.resilience import (CheckpointCorrupt, CheckpointManager,
                                   CheckpointWriteError, FaultTolerantTrainer,
                                   faultinject)
from paddle_tpu.tensor.random import default_generator


def _mse(m, x, y):
    return ((m(x) - y) ** 2).mean()


def _build(seed=7, fused_steps=1, use_sched=False):
    paddle.seed(seed)
    net = nn.Sequential(nn.Linear(6, 12), nn.GELU(), nn.Linear(12, 3))
    sched = lrsched.StepDecay(learning_rate=5e-2, step_size=3,
                              gamma=0.5) if use_sched else None
    opt = paddle.optimizer.AdamW(sched if sched is not None else 5e-2,
                                 parameters=net.parameters())
    step = pjit.CompiledTrainStep(net, _mse, opt, fused_steps=fused_steps)
    return net, opt, step, sched


def _dataset(n_batches, batch=4, seed=3):
    rng = np.random.RandomState(seed)
    return TensorDataset(
        [paddle.to_tensor(rng.randn(n_batches * batch, 6).astype("float32")),
         paddle.to_tensor(rng.randn(n_batches * batch, 3).astype("float32"))])


def _factory(ds, batch=4):
    def loader_factory(epoch):
        return DataLoader(ds, batch_size=batch, shuffle=False)
    return loader_factory


def _params(net):
    net_sd = net.state_dict()
    return {k: np.array(np.asarray(v.numpy()), copy=True)
            for k, v in net_sd.items()}


def _run_steps(step, ds, n, batch=4):
    losses = []
    for i, item in enumerate(DataLoader(ds, batch_size=batch, shuffle=False)):
        if i >= n:
            break
        losses.append(float(step(*item).numpy()))
    return losses


class TestCheckpointManagerRoundtrip:
    def test_roundtrip_restores_exact_state(self, tmp_path):
        net, opt, step, _ = _build()
        ds = _dataset(8)
        _run_steps(step, ds, 3)
        mgr = CheckpointManager(tmp_path, keep_last=3)
        mgr.save(step, 3, cursor={"epoch": 0, "offset": 3})
        saved_params = _params(net)
        saved_rng = np.asarray(default_generator().get_state())
        _run_steps(step, ds, 2)  # diverge past the save point
        for k, v in _params(net).items():
            assert not np.array_equal(v, saved_params[k]), k

        info = mgr.restore(step)
        assert info["step"] == 3
        assert info["cursor"] == {"epoch": 0, "offset": 3}
        for k, v in _params(net).items():
            np.testing.assert_array_equal(v, saved_params[k], err_msg=k)
        np.testing.assert_array_equal(
            np.asarray(default_generator().get_state()), saved_rng)

    def test_restore_returns_none_when_empty(self, tmp_path):
        _, _, step, _ = _build()
        assert CheckpointManager(tmp_path).restore(step) is None
        assert CheckpointManager(tmp_path).latest() is None

    def test_restored_continuation_matches_uninterrupted(self, tmp_path):
        ds = _dataset(8)
        _, _, ref_step, _ = _build(seed=11)
        ref = _run_steps(ref_step, ds, 5)

        net, opt, step, _ = _build(seed=11)
        got = _run_steps(step, ds, 3)
        mgr = CheckpointManager(tmp_path)
        mgr.save(step, 3)
        _run_steps(step, ds, 1)  # wander off; restore must undo this
        mgr.restore(step)
        for i, item in enumerate(DataLoader(ds, batch_size=4, shuffle=False)):
            if i < 3:
                continue
            if i >= 5:
                break
            got.append(float(step(*item).numpy()))
        assert got == ref

    def test_keep_last_gc(self, tmp_path):
        _, _, step, _ = _build()
        ds = _dataset(2)
        _run_steps(step, ds, 1)
        mgr = CheckpointManager(tmp_path, keep_last=2)
        before = counters.snapshot()
        for s in range(1, 6):
            mgr.save(step, s)
        kept = sorted(d for d in os.listdir(tmp_path)
                      if d.startswith("step-"))
        assert kept == ["step-00000004", "step-00000005"]
        assert mgr.latest() == 5
        assert counters.delta(before).get("resilience.gc_removed", 0) == 3

    def test_async_save_overlaps_and_restores(self, tmp_path):
        net, opt, step, _ = _build()
        ds = _dataset(8)
        _run_steps(step, ds, 2)
        mgr = CheckpointManager(tmp_path, async_save=True)
        mgr.save(step, 2)            # write happens on a daemon thread
        saved = _params(net)
        _run_steps(step, ds, 2)      # overlap: training continues
        mgr.wait()
        mgr.restore(step)
        for k, v in _params(net).items():
            np.testing.assert_array_equal(v, saved[k], err_msg=k)

    def test_save_costs_exactly_one_sync(self, tmp_path):
        _, _, step, _ = _build()
        ds = _dataset(4)
        _run_steps(step, ds, 3)  # warm: hydrate + trace done
        mgr = CheckpointManager(tmp_path)
        before = counters.snapshot()
        mgr.save(step, 3)
        d = counters.delta(before)
        assert d.get("jit.syncs", 0) == 1
        assert d.get("jit.host.bind_layer_state", 0) == 1
        assert d.get("jit.host.bind_optimizer_state", 0) == 1
        assert d.get("jit.host.layer_state", 0) == 0
        assert d.get("jit.host.optimizer_state", 0) == 0
        assert d.get("jit.hydrates", 0) == 0
        assert d.get("jit.traces", 0) == 0


class TestAtomicity:
    def test_crash_mid_save_leaves_previous_loadable(self, tmp_path):
        net, opt, step, _ = _build()
        ds = _dataset(8)
        _run_steps(step, ds, 2)
        mgr = CheckpointManager(tmp_path)
        mgr.save(step, 2)  # ordinal 0: clean
        saved = _params(net)
        _run_steps(step, ds, 2)
        # ordinal 1 dies between chunk write and manifest/commit
        with faultinject.fault_schedule("ckpt_crash@1"):
            with pytest.raises(faultinject.SimulatedCrash):
                mgr.save(step, 4)
            assert faultinject.fired == [("ckpt_crash", 1)]
        names = os.listdir(tmp_path)
        assert "step-00000004" not in names           # never committed
        assert any(n.startswith(".tmp-") for n in names)  # crashed staging
        assert mgr.latest() == 2
        info = mgr.restore(step)
        assert info["step"] == 2
        for k, v in _params(net).items():
            np.testing.assert_array_equal(v, saved[k], err_msg=k)

    def test_crash_is_not_swallowed_by_retry(self, tmp_path):
        """SimulatedCrash is a BaseException: the CheckpointManager retry
        loop (``except OSError``) and the trainer's recovery (``except
        recoverable``) must both let it unwind, like a real kill."""
        assert not issubclass(faultinject.SimulatedCrash, Exception)
        _, _, step, _ = _build()
        ds = _dataset(2)
        _run_steps(step, ds, 1)
        mgr = CheckpointManager(tmp_path, retries=5)
        with faultinject.fault_schedule("ckpt_crash@0*5"):
            with pytest.raises(faultinject.SimulatedCrash):
                mgr.save(step, 1)
            assert faultinject.fired == [("ckpt_crash", 0)]  # no retry

    def test_next_successful_save_cleans_stale_tmp(self, tmp_path):
        _, _, step, _ = _build()
        ds = _dataset(4)
        _run_steps(step, ds, 2)
        mgr = CheckpointManager(tmp_path)
        with faultinject.fault_schedule("ckpt_crash@0"):
            with pytest.raises(faultinject.SimulatedCrash):
                mgr.save(step, 2)
        assert any(n.startswith(".tmp-") for n in os.listdir(tmp_path))
        mgr.save(step, 3)
        names = os.listdir(tmp_path)
        assert not any(n.startswith(".tmp-") for n in names)
        assert mgr.latest() == 3


class TestChecksum:
    @staticmethod
    def _corrupt_one_chunk(step_dir, key_prefix="model/"):
        """Rewrite one chunk array inside the npz with flipped bytes: the
        file stays a valid archive, the payload is silently wrong — the
        shape of real disk corruption crc32 exists to catch."""
        fname = next(n for n in os.listdir(step_dir)
                     if n.endswith(".distcp.npz"))
        fpath = os.path.join(step_dir, fname)
        with np.load(fpath) as z:
            arrays = {k: np.array(z[k]) for k in z.files}
        victim = next(k for k in arrays if k.startswith(key_prefix))
        raw = arrays[victim].view(np.uint8).copy()
        raw[0] ^= 0xFF
        arrays[victim] = raw.view(arrays[victim].dtype).reshape(
            arrays[victim].shape)
        with open(fpath, "wb") as f:
            np.savez(f, **arrays)
        return victim, fpath

    def test_corrupt_chunk_raises_naming_it(self, tmp_path):
        _, _, step, _ = _build()
        ds = _dataset(4)
        _run_steps(step, ds, 2)
        mgr = CheckpointManager(tmp_path)
        mgr.save(step, 2)
        victim, fpath = self._corrupt_one_chunk(str(mgr._dir(2)))
        before = counters.snapshot()
        with pytest.raises(CheckpointCorrupt) as ei:
            mgr.restore(step)  # only save is corrupt -> nothing loadable
        msg = str(ei.value)
        assert "checksum mismatch" in str(ei.value.__cause__ or ei.value) \
            or "checksum mismatch" in msg
        # the offending chunk is named somewhere in the chain
        chain = msg + str(ei.value.__cause__ or "")
        assert victim in chain
        assert counters.delta(before).get(
            "resilience.corrupt_detected", 0) >= 1

    def test_corruption_falls_back_to_older_checkpoint(self, tmp_path):
        net, opt, step, _ = _build()
        ds = _dataset(8)
        _run_steps(step, ds, 2)
        mgr = CheckpointManager(tmp_path)
        mgr.save(step, 2)
        older = _params(net)
        _run_steps(step, ds, 2)
        mgr.save(step, 4)
        self._corrupt_one_chunk(str(mgr._dir(4)))
        before = counters.snapshot()
        info = mgr.restore(step)
        assert info["step"] == 2
        for k, v in _params(net).items():
            np.testing.assert_array_equal(v, older[k], err_msg=k)
        d = counters.delta(before)
        assert d.get("resilience.corrupt_detected", 0) >= 1
        assert d.get("resilience.restores", 0) == 1

    def test_truncated_manifest_falls_back(self, tmp_path):
        _, _, step, _ = _build()
        ds = _dataset(8)
        _run_steps(step, ds, 2)
        mgr = CheckpointManager(tmp_path)
        mgr.save(step, 2)
        _run_steps(step, ds, 2)
        mgr.save(step, 4)
        with open(os.path.join(mgr._dir(4), "MANIFEST.json"), "w") as f:
            f.write('{"format": 1, "step":')  # torn write
        with pytest.raises(json.JSONDecodeError):
            json.load(open(os.path.join(mgr._dir(4), "MANIFEST.json")))
        info = mgr.restore(step)
        assert info["step"] == 2


class TestWriteRetry:
    def test_transient_write_error_retried(self, tmp_path):
        _, _, step, _ = _build()
        ds = _dataset(4)
        _run_steps(step, ds, 2)
        mgr = CheckpointManager(tmp_path, retries=3, backoff_s=0.001)
        before = counters.snapshot()
        with faultinject.fault_schedule("ckpt_write@0*2"):
            mgr.save(step, 2)  # attempts 1-2 fail, attempt 3 lands
            assert faultinject.fired == [("ckpt_write", 0)] * 2
        d = counters.delta(before)
        assert d.get("resilience.retries", 0) == 2
        assert d.get("resilience.saves", 0) == 1
        assert d.get("resilience.save_failures", 0) == 0
        assert mgr.latest() == 2
        assert mgr.restore(step)["step"] == 2

    def test_retries_exhausted_raises_write_error(self, tmp_path):
        _, _, step, _ = _build()
        ds = _dataset(4)
        _run_steps(step, ds, 2)
        mgr = CheckpointManager(tmp_path, retries=2, backoff_s=0.001)
        before = counters.snapshot()
        with faultinject.fault_schedule("ckpt_write@0*5"):
            with pytest.raises(CheckpointWriteError):
                mgr.save(step, 2)
        d = counters.delta(before)
        assert d.get("resilience.save_failures", 0) == 1
        assert d.get("resilience.retries", 0) == 2
        assert d.get("resilience.saves", 0) == 0
        assert mgr.latest() is None

    def test_injected_write_error_is_an_ioerror(self):
        assert issubclass(faultinject.InjectedWriteError, IOError)
        assert issubclass(faultinject.InjectedWriteError,
                          faultinject.InjectedFault)


class TestWaitAsyncSave:
    def test_async_failure_surfaced_with_cause(self, tmp_path, monkeypatch):
        boom = OSError("disk gone")

        def bad_savez(f, **kw):
            raise boom
        monkeypatch.setattr(dckpt.np, "savez", bad_savez)
        dckpt.save_state_dict(
            {"w": paddle.to_tensor(np.ones((2, 2), np.float32))},
            str(tmp_path), async_save=True)
        with pytest.raises(RuntimeError, match="async checkpoint save"):
            dckpt.wait_async_save()
        # errors were drained: a second wait is clean
        dckpt.wait_async_save()

    def test_all_errors_surfaced_not_just_first(self):
        with dckpt._ASYNC_LOCK:
            dckpt._ASYNC_ERRORS.extend(
                [OSError("first failure"), OSError("second failure")])
        with pytest.raises(RuntimeError) as ei:
            dckpt.wait_async_save()
        msg = str(ei.value)
        assert "2 async checkpoint saves failed" in msg
        assert "first failure" in msg and "second failure" in msg
        assert isinstance(ei.value.__cause__, OSError)
        dckpt.wait_async_save()  # drained

    def test_concurrent_waiters_all_complete(self):
        release = threading.Event()
        writer = threading.Thread(target=release.wait, daemon=True)
        with dckpt._ASYNC_LOCK:
            dckpt._ASYNC_THREADS.append(writer)
        writer.start()
        results = []

        def waiter():
            try:
                dckpt.wait_async_save()
                results.append("ok")
            except BaseException as e:  # pragma: no cover - fail loudly
                results.append(e)
        waiters = [threading.Thread(target=waiter) for _ in range(4)]
        for t in waiters:
            t.start()
        time.sleep(0.05)      # all four are blocked joining the writer
        release.set()
        for t in waiters:
            t.join(timeout=5)
        assert results == ["ok"] * 4
        assert not dckpt._ASYNC_THREADS

    def test_save_is_readable_after_wait(self, tmp_path):
        w = np.arange(6, dtype=np.float32).reshape(2, 3)
        dckpt.save_state_dict({"w": paddle.to_tensor(w)}, str(tmp_path),
                              async_save=True)
        dckpt.wait_async_save()
        tgt = {"w": paddle.to_tensor(np.zeros((2, 3), np.float32))}
        dckpt.load_state_dict(tgt, str(tmp_path))
        np.testing.assert_array_equal(np.asarray(tgt["w"].numpy()), w)


class TestPrefetcherCursor:
    def test_device_prefetcher_skip_replay(self):
        ds = _dataset(6)
        loader = DataLoader(ds, batch_size=4, shuffle=False)
        full_before = counters.snapshot()
        full = [tuple(np.asarray(t.numpy()) for t in b)
                for b in DevicePrefetcher(loader, depth=2)]
        full_puts = counters.delta(full_before).get("io.device_put_calls", 0)
        assert len(full) == 6

        before = counters.snapshot()
        pref = DevicePrefetcher(DataLoader(ds, batch_size=4, shuffle=False),
                                depth=2, start_offset=2)
        assert len(pref) == 4
        got = [tuple(np.asarray(t.numpy()) for t in b) for b in pref]
        d = counters.delta(before)
        assert pref.consumed == 6
        assert d.get("io.skipped_batches", 0) == 2
        # skipped batches never hit the device: 4/6 of the full run's puts
        assert d.get("io.device_put_calls", 0) == full_puts * 4 // 6
        assert len(got) == 4
        for g, f in zip(got, full[2:]):
            for a, b in zip(g, f):
                np.testing.assert_array_equal(a, b)

    def test_stacking_prefetcher_resume_alignment(self):
        ds = _dataset(8)
        full = list(StackingPrefetcher(
            DataLoader(ds, batch_size=4, shuffle=False), 2))
        assert len(full) == 4
        pref = StackingPrefetcher(DataLoader(ds, batch_size=4, shuffle=False),
                                  2, start_offset=4)
        got = list(pref)
        assert len(got) == 2
        assert pref.consumed == 8
        for gwin, fwin in zip(got, full[2:]):
            assert gwin.k == fwin.k == 2
            for a, b in zip(gwin, fwin):
                np.testing.assert_array_equal(np.asarray(a.numpy()),
                                              np.asarray(b.numpy()))


class _Baseline:
    """Uninterrupted trainer run: the bit-identity reference."""

    def __init__(self, tmp_path, steps=10, save_every=4, fused_steps=1,
                 use_sched=False, n_batches=12, seed=7):
        net, opt, step, sched = _build(seed=seed, fused_steps=fused_steps,
                                       use_sched=use_sched)
        ds = _dataset(n_batches)
        trainer = FaultTolerantTrainer(
            step, _factory(ds), CheckpointManager(tmp_path, keep_last=2),
            scheduler=sched, epochs=2, max_steps=steps,
            save_every=save_every)
        self.losses = trainer.run()
        self.params = _params(net)
        self.rng = np.asarray(default_generator().get_state())
        self.lr = opt.get_lr()
        self.ds, self.seed = ds, seed
        self.fused_steps, self.use_sched = fused_steps, use_sched
        self.steps, self.save_every = steps, save_every

    def faulted_run(self, tmp_path, schedule, expect_recoveries=1,
                    **trainer_kw):
        net, opt, step, sched = _build(seed=self.seed,
                                       fused_steps=self.fused_steps,
                                       use_sched=self.use_sched)
        before = counters.snapshot()
        with faultinject.fault_schedule(schedule):
            trainer = FaultTolerantTrainer(
                step, _factory(self.ds),
                CheckpointManager(tmp_path, keep_last=2),
                scheduler=sched, epochs=2, max_steps=self.steps,
                save_every=self.save_every, **trainer_kw)
            losses = trainer.run()
        assert trainer.recoveries == expect_recoveries
        d = counters.delta(before)
        assert d.get("resilience.recoveries", 0) == expect_recoveries
        assert d.get("resilience.restores", 0) == expect_recoveries
        return net, opt, losses, d


class TestBitIdenticalResume:
    def test_preempt_resume_bit_identity(self, tmp_path):
        """THE flagship: 10 straight steps vs 4 + preempt + restore + 6 —
        identical losses, params, RNG chain, LR."""
        base = _Baseline(tmp_path / "base", use_sched=True)
        net, opt, losses, d = base.faulted_run(tmp_path / "faulted",
                                               "preempt@4")
        assert d.get("resilience.recovered.SimulatedPreemption", 0) == 1
        assert losses == base.losses          # all 10, bit-equal floats
        for k, v in _params(net).items():
            np.testing.assert_array_equal(v, base.params[k], err_msg=k)
        np.testing.assert_array_equal(
            np.asarray(default_generator().get_state()), base.rng)
        assert opt.get_lr() == base.lr

    def test_preempt_resume_bit_identity_fused(self, tmp_path):
        """Same contract through the fused-window (StackingPrefetcher /
        scan-dispatch) path: preemption between windows."""
        base = _Baseline(tmp_path / "base", steps=8, fused_steps=2)
        net, _, losses, _ = base.faulted_run(tmp_path / "faulted",
                                             "preempt@4")
        assert losses == base.losses
        for k, v in _params(net).items():
            np.testing.assert_array_equal(v, base.params[k], err_msg=k)

    def test_preempt_mid_save_interval(self, tmp_path):
        """Preemption at step 6 restores the step-4 checkpoint and replays
        5-6; the replayed entries overwrite bit-identically."""
        base = _Baseline(tmp_path / "base")
        _, _, losses, d = base.faulted_run(tmp_path / "faulted", "preempt@6")
        assert losses == base.losses
        assert d.get("io.skipped_batches", 0) == 4  # replay from offset 4

    def test_loader_fault_recovery(self, tmp_path):
        base = _Baseline(tmp_path / "base")
        _, _, losses, d = base.faulted_run(tmp_path / "faulted", "loader@6")
        assert d.get("resilience.recovered.InjectedLoaderError", 0) == 1
        assert losses == base.losses

    def test_nan_loss_recovery(self, tmp_path):
        """A poisoned batch NaNs the loss; the trainer restores the last
        good checkpoint and the replay (schedule consumed) is clean — the
        final trajectory matches the baseline bit-for-bit."""
        base = _Baseline(tmp_path / "base")
        net, _, losses, d = base.faulted_run(tmp_path / "faulted",
                                             "nan_loss@5")
        assert d.get("resilience.recovered.NonFiniteLossError", 0) == 1
        assert all(np.isfinite(v) for v in losses.values())
        assert losses == base.losses
        for k, v in _params(net).items():
            np.testing.assert_array_equal(v, base.params[k], err_msg=k)

    def test_multiple_faults_one_run(self, tmp_path):
        base = _Baseline(tmp_path / "base")
        _, _, losses, d = base.faulted_run(
            tmp_path / "faulted", "preempt@3;nan_loss@7",
            expect_recoveries=2)
        assert losses == base.losses
        assert d.get("resilience.faults_injected", 0) == 2

    def test_restart_from_disk_resumes(self, tmp_path):
        """Process-death shape: a NEW trainer (fresh model, different init
        seed) over the same checkpoint dir resumes from the last save and
        converges to the uninterrupted trajectory."""
        base = _Baseline(tmp_path / "base", steps=8)
        ck = tmp_path / "faulted"
        net1, _, step1, _ = _build(seed=7)
        t1 = FaultTolerantTrainer(step1, _factory(base.ds),
                                  CheckpointManager(ck, keep_last=2),
                                  epochs=2, max_steps=4, save_every=4)
        first = t1.run()
        assert sorted(first) == [1, 2, 3, 4]
        # "restart": different init seed — restore overwrites everything
        net2, _, step2, _ = _build(seed=99)
        t2 = FaultTolerantTrainer(step2, _factory(base.ds),
                                  CheckpointManager(ck, keep_last=2),
                                  epochs=2, max_steps=8, save_every=4)
        second = t2.run()
        assert sorted(second) == [5, 6, 7, 8]  # no replay of committed work
        for s in (5, 6, 7, 8):
            assert second[s] == base.losses[s]
        for k, v in _params(net2).items():
            np.testing.assert_array_equal(v, base.params[k], err_msg=k)

    def test_max_recoveries_exhausted_reraises(self, tmp_path):
        _, _, step, _ = _build()
        ds = _dataset(6)
        with faultinject.fault_schedule("preempt@2*10"):
            trainer = FaultTolerantTrainer(
                step, _factory(ds), CheckpointManager(tmp_path),
                epochs=1, max_steps=6, save_every=100, max_recoveries=2)
            with pytest.raises(faultinject.SimulatedPreemption):
                trainer.run()
        assert trainer.recoveries == 3  # 2 recovered + the fatal third


class TestScalerState:
    def test_grad_scaler_state_rides_the_checkpoint(self, tmp_path):
        from paddle_tpu.amp import GradScaler

        def build():
            paddle.seed(13)
            net = nn.Linear(6, 3)
            opt = paddle.optimizer.AdamW(5e-2, parameters=net.parameters())
            scaler = GradScaler(init_loss_scaling=1024.0,
                                incr_every_n_steps=2)
            return net, pjit.CompiledTrainStep(net, _mse, opt, scaler=scaler)

        ds = _dataset(8)
        net, step = build()
        _run_steps(step, ds, 3)  # dynamic loss scale moves (incr_every=2)
        mgr = CheckpointManager(tmp_path)
        mgr.save(step, 3)
        saved = step.scaler.state_dict()
        assert saved["scale"] != 1024.0  # the trajectory actually moved
        _run_steps(step, ds, 2)
        assert step.scaler.state_dict() != saved
        mgr.restore(step)
        assert step.scaler.state_dict() == saved


class TestSchedulerState:
    def test_reduce_on_plateau_roundtrip(self):
        a = lrsched.ReduceOnPlateau(learning_rate=0.1, factor=0.5,
                                    patience=1, cooldown=1)
        for m in (1.0, 1.0, 1.0, 0.2, 0.5):
            a.step(m)
        sd = a.state_dict()
        for k in ("best", "num_bad", "cooldown_counter", "last_lr"):
            assert k in sd
        b = lrsched.ReduceOnPlateau(learning_rate=0.1, factor=0.5,
                                    patience=1, cooldown=1)
        b.set_state_dict(sd)
        assert b.last_lr == a.last_lr
        assert b.best == a.best
        assert b.num_bad == a.num_bad
        assert b.cooldown_counter == a.cooldown_counter
        # identical subsequent trajectory
        for m in (0.9, 0.9, 0.9):
            a.step(m)
            b.step(m)
            assert a.last_lr == b.last_lr


class TestFaultInject:
    def test_spec_parsing(self):
        sched = faultinject._parse("ckpt_write@1*2; preempt@4, nan_loss@7")
        assert sched == {("ckpt_write", 1): 2, ("preempt", 4): 1,
                         ("nan_loss", 7): 1}
        with pytest.raises(ValueError, match="bad fault schedule"):
            faultinject._parse("preempt4")

    def test_take_consumes_and_counts(self):
        before = counters.snapshot()
        with faultinject.fault_schedule("nan_loss@3*2"):
            assert not faultinject.take("nan_loss", 2)
            assert faultinject.take("nan_loss", 3)
            assert faultinject.take("nan_loss", 3)
            assert not faultinject.take("nan_loss", 3)  # exhausted
            assert faultinject.fired == [("nan_loss", 3)] * 2
        d = counters.delta(before)
        assert d.get("resilience.faults_injected", 0) == 2
        assert d.get("resilience.faults_injected.nan_loss", 0) == 2
        assert not faultinject.active()

    def test_maybe_fault_raises_site_exception(self):
        with faultinject.fault_schedule("loader@5"):
            faultinject.maybe_fault("loader", 4)  # not scheduled: no-op
            with pytest.raises(faultinject.InjectedLoaderError):
                faultinject.maybe_fault("loader", 5)
            faultinject.maybe_fault("loader", 5)  # consumed: no-op

    def test_flag_driven_schedule(self):
        from paddle_tpu.core import flags as cflags
        try:
            cflags.set_flags({"FLAGS_fault_schedule": "preempt@9"})
            assert faultinject.active()
            with pytest.raises(faultinject.SimulatedPreemption):
                faultinject.maybe_fault("preempt", 9)
        finally:
            cflags.set_flags({"FLAGS_fault_schedule": ""})
        assert not faultinject.active()
