"""Auto-tuner: parallel-config search (reference:
distributed/auto_tuner/tuner.py:21, search.py:31-144, prune.py)."""

import numpy as np
import pytest

from paddle_tpu.distributed.auto_tuner import AutoTuner, TuneSpace, tune


def gpt_1_3b(n_devices=8, global_batch=64, hbm=15.75e9):
    return TuneSpace(n_devices=n_devices, num_layers=24, hidden_size=2048,
                     num_heads=16, vocab_size=50304, seq_len=1024,
                     global_batch=global_batch, hbm_bytes=hbm)


class TestPruning:
    def test_divisibility_rules(self):
        t = AutoTuner(gpt_1_3b())
        from paddle_tpu.distributed.auto_tuner import Candidate
        assert "num_layers" in t.prune_reason(Candidate(1, 1, 5, 1, 8))
        assert "num_heads" in t.prune_reason(
            Candidate(1, 32, 1, 1, 8, 0, 0))
        assert "global_batch" in t.prune_reason(Candidate(8, 1, 1, 1, 32))
        assert "mb" in t.prune_reason(Candidate(1, 1, 8, 1, 4))

    def test_memory_prunes_single_chip_1_3b(self):
        """1.3B with AdamW state cannot sit on one chip (scripts/
        PERF_NOTES.md) — the dp8 pure-data-parallel candidate must be
        memory-pruned."""
        t = AutoTuner(gpt_1_3b())
        from paddle_tpu.distributed.auto_tuner import Candidate
        reason = t.prune_reason(Candidate(8, 1, 1, 1, 8))
        assert reason is not None and "HBM" in reason, reason

    def test_all_pruned_raises_with_reasons(self):
        space = gpt_1_3b(n_devices=1, hbm=1e9)  # nothing fits 1G
        with pytest.raises(ValueError, match="every candidate pruned"):
            AutoTuner(space).tune()


class TestSearch:
    def test_finds_model_parallel_config_for_1_3b(self):
        """On 8 chips the tuner must pick a config that actually shards the
        1.3B state (mp, pp, or sharding > 1) and fits HBM."""
        best = AutoTuner(gpt_1_3b()).tune()
        assert best.mp * best.pp * best.sharding > 1, best
        assert best.est_hbm <= 15.75e9
        assert best.dp * best.mp * best.pp * best.sharding == 8

    def test_small_model_prefers_pure_dp(self):
        """A 125M model fits everywhere; pure data parallel has zero TP/PP
        comm and must win the analytic ranking."""
        space = TuneSpace(n_devices=8, num_layers=12, hidden_size=768,
                          num_heads=12, vocab_size=50304, seq_len=1024,
                          global_batch=64)
        best = AutoTuner(space).tune()
        assert best.mp == 1 and best.pp == 1, best

    def test_trial_fn_overrides_ranking(self):
        t = AutoTuner(gpt_1_3b())
        calls = []

        def trial(c):
            calls.append(c)
            # pretend the LAST tried candidate is fastest
            return 1.0 / (len(calls))

        best = t.tune(trial_fn=trial, top_n=3)
        assert best.measured is not None
        assert best is calls[-1]
        assert len(calls) == 3

    def test_trial_failures_fall_back(self):
        t = AutoTuner(gpt_1_3b())
        best = t.tune(trial_fn=lambda c: (_ for _ in ()).throw(
            RuntimeError("oom")), top_n=2)
        assert best is not None  # analytic winner survives

    def test_convenience_entry(self):
        best = tune(n_devices=8, num_layers=24, hidden_size=2048,
                    num_heads=16, vocab_size=50304, seq_len=1024,
                    global_batch=64)
        assert best.dp * best.mp * best.pp * best.sharding == 8
