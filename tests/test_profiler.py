"""Observability layer: host tracer, Profiler scheduler, counter registry,
NaN/Inf guard, and the counter-verified steady-state gate."""

import json
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.jit as pjit
import paddle_tpu.nn as nn
from paddle_tpu import profiler
from paddle_tpu.core import flags as core_flags
from paddle_tpu.profiler import (ProfilerState, ProfilerTarget, counters,
                                 host_tracer, make_scheduler)


@pytest.fixture(autouse=True)
def _restore_trace_flags():
    """Tests toggle process-global flags; leave them as found."""
    level = core_flags.flag("FLAGS_host_trace_level")
    nan = core_flags.flag("FLAGS_check_nan_inf")
    yield
    core_flags.set_flags({"FLAGS_host_trace_level": level,
                          "FLAGS_check_nan_inf": nan})
    if host_tracer.is_collecting():
        host_tracer.stop()


def _tiny_step(poison=False):
    paddle.seed(0)
    model = nn.Linear(8, 4)
    opt = paddle.optimizer.AdamW(1e-3, parameters=model.parameters())
    x = paddle.randn([4, 8])
    y = paddle.randn([4, 4])

    def loss_fn(m, a, b):
        loss = ((m(a) - b) ** 2).mean()
        if poison:
            loss = paddle.log(loss - 1e9)  # log(negative) -> nan
        return loss

    return pjit.CompiledTrainStep(model, loss_fn, opt), x, y


class TestMakeScheduler:
    def test_state_sequence(self):
        sched = make_scheduler(closed=1, ready=1, record=2, repeat=2,
                               skip_first=2)
        S = ProfilerState
        want = [S.CLOSED, S.CLOSED,                           # skip_first
                S.CLOSED, S.READY, S.RECORD, S.RECORD_AND_RETURN,  # window 1
                S.CLOSED, S.READY, S.RECORD, S.RECORD_AND_RETURN,  # window 2
                S.CLOSED, S.CLOSED]                           # repeat done
        assert [sched(i) for i in range(len(want))] == want

    def test_record_one_is_record_and_return(self):
        sched = make_scheduler(closed=0, ready=0, record=1)
        assert sched(0) == ProfilerState.RECORD_AND_RETURN
        assert sched(7) == ProfilerState.RECORD_AND_RETURN

    def test_repeat_zero_repeats_forever(self):
        sched = make_scheduler(closed=1, ready=0, record=1, repeat=0)
        assert sched(999) == ProfilerState.RECORD_AND_RETURN
        assert sched(998) == ProfilerState.CLOSED

    @pytest.mark.parametrize("record", [0, -1, 1.5, "2"])
    def test_record_must_be_positive_int(self, record):
        with pytest.raises(ValueError, match="record should be a positive"):
            make_scheduler(closed=1, ready=1, record=record)

    @pytest.mark.parametrize("kw", ["closed", "ready", "repeat", "skip_first"])
    def test_nonnegative_args_validated(self, kw):
        kwargs = dict(closed=1, ready=1, record=1, repeat=0, skip_first=0)
        kwargs[kw] = -1
        with pytest.raises(ValueError,
                           match=f"{kw} should be a non-negative integer"):
            make_scheduler(**kwargs)


class TestHostTracer:
    def test_disabled_level_returns_null_singleton(self):
        core_flags.set_flags({"FLAGS_host_trace_level": 0})
        host_tracer.start()
        try:
            s1 = host_tracer.span("a")
            s2 = host_tracer.span("b")
            assert s1 is s2  # shared no-op: zero allocation when off
            with s1:
                pass
            assert host_tracer.span_count() == 0
        finally:
            host_tracer.stop()

    def test_no_session_records_nothing(self):
        core_flags.set_flags({"FLAGS_host_trace_level": 1})
        assert not host_tracer.is_collecting()
        before = host_tracer.span_count()
        with host_tracer.span("orphan"):
            pass
        assert host_tracer.span_count() == before

    def test_level2_sites_gated(self):
        core_flags.set_flags({"FLAGS_host_trace_level": 1})
        host_tracer.start()
        try:
            with host_tracer.span("fine_grained", level=2):
                pass
            with host_tracer.span("coarse", level=1):
                pass
            names = [e[0] for e in host_tracer.events()]
            assert names == ["coarse"]
        finally:
            host_tracer.stop()

    def test_nested_spans_and_multithread_tids(self):
        core_flags.set_flags({"FLAGS_host_trace_level": 1})
        host_tracer.start()

        def worker():
            with host_tracer.span("worker_outer"):
                with host_tracer.span("worker_inner"):
                    pass

        try:
            with host_tracer.span("main_outer"):
                assert host_tracer.current_stack() == ["main_outer"]
                with host_tracer.span("main_inner"):
                    assert host_tracer.current_stack() == ["main_outer",
                                                           "main_inner"]
            t = threading.Thread(target=worker, name="trace_worker")
            t.start()
            t.join()
        finally:
            evts = host_tracer.stop()

        by_name = {e[0]: e for e in evts}
        assert by_name["main_inner"][4] == 1      # depth
        assert by_name["main_outer"][4] == 0
        main_tid = by_name["main_outer"][1]
        worker_tid = by_name["worker_outer"][1]
        assert main_tid != worker_tid
        # nesting: inner interval inside outer interval, same thread
        assert by_name["main_inner"][1] == main_tid
        assert (by_name["main_outer"][2] <= by_name["main_inner"][2]
                and by_name["main_inner"][3] <= by_name["main_outer"][3])

        trace = host_tracer.to_chrome_trace(evts)
        # loadable chrome trace-event JSON
        trace = json.loads(json.dumps(trace))
        xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
        ms = [e for e in trace["traceEvents"] if e["ph"] == "M"]
        assert {e["name"] for e in xs} == {"main_outer", "main_inner",
                                           "worker_outer", "worker_inner"}
        assert len({e["tid"] for e in xs}) == 2
        assert any(e["name"] == "thread_name"
                   and e["args"]["name"] == "trace_worker" for e in ms)
        for e in xs:
            assert e["dur"] >= 0 and isinstance(e["ts"], float)

    def test_summary_table(self):
        core_flags.set_flags({"FLAGS_host_trace_level": 1})
        host_tracer.start()
        try:
            for _ in range(3):
                with host_tracer.span("repeated"):
                    pass
        finally:
            evts = host_tracer.stop()
        table = host_tracer.summary(evts)
        assert "repeated" in table and "Calls" in table
        row = next(l for l in table.splitlines() if l.startswith("repeated"))
        assert row.split()[1] == "3"


class TestCounters:
    def test_inc_get_snapshot_delta(self):
        counters.reset("test.alpha")
        counters.reset("test.beta")
        before = counters.snapshot()
        counters.inc("test.alpha")
        counters.inc("test.alpha", 4)
        counters.inc("test.beta", 2)
        assert counters.get("test.alpha") == 5
        d = counters.delta(before)
        assert d["test.alpha"] == 5 and d["test.beta"] == 2
        # zero-movement keys are dropped from deltas
        assert all(v != 0 for v in d.values())

    def test_reset(self):
        counters.inc("test.gamma", 7)
        counters.reset("test.gamma")
        assert counters.get("test.gamma") == 0
        counters.inc("test.gamma", 1)
        counters.reset()
        assert counters.get("test.gamma") == 0

    def test_gauge(self):
        counters.set_gauge("test.gauge", 42)
        assert counters.snapshot()["test.gauge"] == 42

    def test_allreduce_single_process_is_snapshot(self):
        counters.inc("test.ar", 3)
        red = counters.allreduce()
        assert red["test.ar"] == counters.get("test.ar")


class TestProfilerFrontend:
    def test_three_step_run_summary_and_chrome_trace(self, tmp_path):
        core_flags.set_flags({"FLAGS_host_trace_level": 1})
        step, x, y = _tiny_step()
        handler = profiler.export_chrome_tracing(str(tmp_path), "w0")
        with profiler.Profiler(targets=[ProfilerTarget.CPU],
                               on_trace_ready=handler) as prof:
            for _ in range(3):
                step(x, y)
                prof.step()
        assert prof._chrome_trace_path.endswith("w0.pt.trace.json")
        trace = profiler.load_profiler_result(prof._chrome_trace_path)
        names = {e["name"] for e in trace["traceEvents"] if e["ph"] == "X"}
        # acceptance: spans from the jit hot path present in the export
        assert {"jit.step", "jit.dispatch", "jit.hydrate"} <= names
        assert "optimizer.step" in names  # traced during step-1 compile
        table = prof.summary()
        assert "jit.step" in table and "Calls" in table
        assert "jit.step" in profiler.summary()  # module-level convenience

    def test_scheduler_windows_collect_only_record_steps(self):
        core_flags.set_flags({"FLAGS_host_trace_level": 1})
        ready_count = [0]
        prof = profiler.Profiler(
            scheduler=make_scheduler(closed=1, ready=0, record=1, repeat=1),
            on_trace_ready=lambda p: ready_count.__setitem__(
                0, ready_count[0] + 1))
        prof.start()
        for i in range(4):
            with profiler.RecordEvent(f"user_step_{i}"):
                pass
            prof.step()
        prof.stop()
        names = {e[0] for e in prof._events}
        assert "user_step_1" in names       # the RECORD_AND_RETURN step
        assert "user_step_0" not in names   # CLOSED step
        assert ready_count[0] == 1

    def test_timer_only_step_info(self):
        prof = profiler.Profiler(timer_only=True)
        prof.start()
        for _ in range(3):
            prof.step(num_samples=8)
        info = prof.step_info()
        prof.stop()
        assert "reader_cost:" in info and "batch_cost:" in info
        ips = float(info.split("ips:")[1].split()[0])
        assert ips > 0
        assert "samples/s" in info
        # window resets after step_info (paddle semantics)
        assert prof.step_info() == "(no steps recorded)"

    def test_record_event_begin_end(self):
        core_flags.set_flags({"FLAGS_host_trace_level": 1})
        host_tracer.start()
        try:
            ev = profiler.RecordEvent("manual")
            ev.begin()
            ev.end()
            assert [e[0] for e in host_tracer.events()] == ["manual"]
        finally:
            host_tracer.stop()


class TestNanInfGuard:
    def test_poisoned_loss_raises_with_span_context(self):
        core_flags.set_flags({"FLAGS_check_nan_inf": 1})
        step, x, y = _tiny_step(poison=True)
        with pytest.raises(FloatingPointError,
                           match="FLAGS_check_nan_inf: non-finite"):
            step(x, y)

    def test_clean_loss_passes_with_guard_on(self):
        core_flags.set_flags({"FLAGS_check_nan_inf": 1})
        step, x, y = _tiny_step()
        loss = step(x, y)
        assert np.isfinite(float(loss.numpy()))
        assert True in step._jits  # guard variant compiled

    def test_guard_off_is_zero_overhead(self):
        core_flags.set_flags({"FLAGS_check_nan_inf": 0})
        step, x, y = _tiny_step(poison=True)
        loss = step(x, y)  # no raise: checks not traced into the program
        assert not np.isfinite(float(loss.numpy()))
        assert set(step._jits) == {False}  # only the unguarded jit entry

    def test_toggling_flag_switches_jit_entry(self):
        step, x, y = _tiny_step()
        core_flags.set_flags({"FLAGS_check_nan_inf": 0})
        step(x, y)
        core_flags.set_flags({"FLAGS_check_nan_inf": 1})
        step(x, y)
        assert set(step._jits) == {False, True}


class TestSteadyStateZeroTracing:
    def test_level0_steady_step_records_zero_spans(self):
        """Acceptance: FLAGS_host_trace_level=0 -> a steady-state step makes
        zero span records even inside an active collection session."""
        step, x, y = _tiny_step()
        for _ in range(3):
            step(x, y)  # warm: hydrate + both traces done
        core_flags.set_flags({"FLAGS_host_trace_level": 0})
        host_tracer.start()
        try:
            before = counters.snapshot()
            step(x, y)
            d = counters.delta(before)
            assert host_tracer.span_count() == 0
            assert d.get("jit.cache_hits") == 1  # it really was a steady step
        finally:
            host_tracer.stop()


class TestCheckCountersGate:
    def test_steady_state_counter_gate(self):
        import importlib.util
        import pathlib
        path = (pathlib.Path(__file__).resolve().parent.parent / "scripts"
                / "check_counters.py")
        spec = importlib.util.spec_from_file_location("check_counters", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        result = mod.run()
        assert result["value"] == 0
