"""Tensor-parallel serving over the StateArena (paddle_tpu.serving.arena).

The load-bearing contracts: (1) a mesh(1,1) arena is INVISIBLE — engines
key, compile, count and emit bit-identically to unsharded ones; (2) an
mp2 engine is token-identical to single-device for greedy AND seeded
sampling, with the KV pool's head axis actually sharded per chip;
(3) indivisible head counts soft-degrade to replicated
(``serving.mesh.spec_degraded``) instead of failing at compile time;
(4) the arena's LRU'd program cache accounts hits / misses / evictions /
rebuilds truthfully.
"""

import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.profiler import counters

PROMPTS = [[5, 9, 11], [7, 3], [5, 9, 2, 4]]
SAMPLE = dict(do_sample=True, temperature=0.9, top_k=8)

# counters whose deltas must match exactly between an unsharded engine
# and a mesh(1,1) arena engine over the same workload (fresh model each,
# so both sides trace cold)
PARITY = ("serving.retraces", "serving.requests", "serving.prefill_batches",
          "serving.decode_steps", "serving.decode_tokens",
          "serving.kv.prefill_chunks", "serving.kv.quant.prefill_tokens",
          "serving.kv.quant.decode_tokens", "serving.spec.drafted",
          "serving.spec.accepted", "serving.spec.verify_steps",
          "kernels.paged.xla_fallbacks", "dist.collective_launches")


def _fresh_model(seed=0, heads=4, hidden=32):
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    cfg = GPTConfig(vocab_size=64, hidden_size=hidden, num_layers=2,
                    num_heads=heads, max_seq_len=32,
                    use_flash_attention=False)
    paddle.seed(seed)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


def _fresh_draft(seed=1):
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    paddle.seed(seed)
    d = GPTForCausalLM(GPTConfig(vocab_size=64, hidden_size=16,
                                 num_layers=1, num_heads=2, max_seq_len=32,
                                 use_flash_attention=False))
    d.eval()
    return d


def _paged(m, **kw):
    from paddle_tpu.serving import LLMEngine
    kw.setdefault("max_slots", 3)
    kw.setdefault("max_seq_len", 32)
    kw.setdefault("min_bucket", 4)
    kw.setdefault("block_size", 4)
    kw.setdefault("prefill_chunk", 8)
    return LLMEngine(m, kv_layout="paged", **kw)


def _run(eng, sampled=False, limit=300):
    hs = [eng.add_request(p, max_new_tokens=5, seed=21 + i,
                          **(SAMPLE if sampled else {}))
          for i, p in enumerate(PROMPTS)]
    n = 0
    while not all(h.is_finished for h in hs):
        eng.step()
        n += 1
        assert n < limit, "engine did not converge"
    return [list(map(int, h.tokens)) for h in hs]


def _mesh(n):
    import jax
    from jax.sharding import Mesh
    if jax.device_count() < n:
        pytest.skip(f"needs {n} devices, have {jax.device_count()}")
    return Mesh(np.array(jax.devices()[:n]).reshape(n), ("mp",))


def _measure(build, sampled=False):
    before = counters.snapshot()
    eng = build()
    toks = _run(eng, sampled=sampled)
    delta = counters.delta(before)
    return toks, {k: delta.get(k, 0) for k in PARITY}


# ---------------------------------------------------------------------------
# mesh(1,1): the arena must be invisible
# ---------------------------------------------------------------------------

@pytest.mark.slow  # tier-1 invisibility coverage: tag/programs-shared test
def test_mesh1_int8_engine_bit_identical_with_counter_parity():
    mesh = _mesh(1)
    toks, d = _measure(lambda: _paged(_fresh_model(), kv_dtype="int8"),
                       sampled=True)
    toks_m, d_m = _measure(
        lambda: _paged(_fresh_model(), kv_dtype="int8", mesh=mesh),
        sampled=True)
    assert toks == toks_m
    assert d == d_m


@pytest.mark.slow  # four engine builds (two draft/target pairs)
def test_mesh1_speculative_engine_bit_identical_with_counter_parity():
    mesh = _mesh(1)
    toks, d = _measure(
        lambda: _paged(_fresh_model(), draft_model=_fresh_draft(), spec_k=2))
    toks_m, d_m = _measure(
        lambda: _paged(_fresh_model(), draft_model=_fresh_draft(), spec_k=2,
                       mesh=mesh))
    assert toks == toks_m
    assert d == d_m


def test_mesh1_tag_empty_and_programs_shared():
    from paddle_tpu.serving.engine import _model_programs
    mesh = _mesh(1)
    m = _fresh_model()
    e1 = _paged(m)
    _run(e1)
    n_programs = len(_model_programs(m))
    e2 = _paged(m, mesh=mesh)
    assert e2.arena.tag == ""
    _run(e2)
    # mesh(1,1) keys identically: the warm cache served every program
    assert len(_model_programs(m)) == n_programs


# ---------------------------------------------------------------------------
# mp2: token identity + real sharding
# ---------------------------------------------------------------------------

def test_mp2_token_identity_greedy_and_seeded():
    mesh = _mesh(2)
    m = _fresh_model()
    base_g = _run(_paged(m))
    base_s = _run(_paged(m), sampled=True)
    eng = _paged(m, mesh=mesh)
    assert _run(eng) == base_g
    assert _run(_paged(m, mesh=mesh), sampled=True) == base_s
    # the KV pool's head axis is actually sharded per chip
    L, nb, bs, nh, hd = 2, eng.n_blocks, 4, 4, 8
    assert eng.arena.shard_shape("pool_k") == (L, nb, bs, nh // 2, hd)
    assert eng.arena.kv_head_axis
    assert eng.stats()["mesh_tag"] == "[mp2]"


def test_mp2_per_chip_bytes_halve_kv_pool():
    mesh = _mesh(2)
    m = _fresh_model()
    single = _paged(m)
    sharded = _paged(m, mesh=mesh)
    kv1 = single.arena.device_bytes("pool_k", "pool_v")
    kv2 = sharded.arena.device_bytes("pool_k", "pool_v")
    assert kv2 * 2 == kv1
    w1 = single.arena.device_bytes("weights")
    w2 = sharded.arena.device_bytes("weights")
    assert w2 < w1  # matrices shard; norms/biases replicate


@pytest.mark.slow  # tier-1 mp2 coverage: greedy/seeded identity test
def test_mp2_int8_engine_token_identity():
    mesh = _mesh(2)
    m = _fresh_model()
    base = _run(_paged(m, kv_dtype="int8"), sampled=True)
    assert _run(_paged(m, kv_dtype="int8", mesh=mesh), sampled=True) == base


@pytest.mark.slow  # interpret-mode pallas sweep
def test_mp2_pallas_shard_map_token_identity():
    import paddle_tpu.kernels.paged_attention as _pa
    from paddle_tpu.core import flags as pflags
    mesh = _mesh(2)
    m = _fresh_model()
    base = _run(_paged(m))
    _pa._INTERPRET[0] = True
    pflags.set_flags({"FLAGS_paged_kernel": "pallas"})
    try:
        eng = _paged(m, mesh=mesh)
        assert _run(eng) == base
        assert eng.arena.kv_head_axis
    finally:
        _pa._INTERPRET[0] = False
        pflags.set_flags({"FLAGS_paged_kernel": "off"})


def test_mp2_fleet_replicas_construct_mesh_engines():
    from paddle_tpu.serving import ServingFleet
    mesh = _mesh(2)
    m = _fresh_model()
    fleet = ServingFleet(m, replicas=1, max_slots=3, max_seq_len=32,
                         min_bucket=4, kv_layout="paged", block_size=4,
                         prefill_chunk=8, mesh=mesh)
    try:
        rep = fleet._replicas[0]
        assert rep.engine.arena.multi_device
        assert rep.engine.arena.tag == "[mp2]"
        h = fleet.submit(PROMPTS[0], max_new_tokens=4)
        h.wait()
        assert len(h.tokens) > 0
    finally:
        fleet.drain()


# ---------------------------------------------------------------------------
# soft-degrade: indivisible heads
# ---------------------------------------------------------------------------

@pytest.mark.slow  # tier-1 degrade coverage: the validate_spec/resolve_spec
# unit tests below exercise both paths
def test_indivisible_heads_degrade_to_replicated_and_stay_identical():
    mesh = _mesh(2)
    m = _fresh_model(seed=3, heads=3, hidden=24)   # nh=3, mp=2
    base = _run(_paged(m))
    before = counters.get("serving.mesh.spec_degraded")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        eng = _paged(m, mesh=mesh)
    assert counters.get("serving.mesh.spec_degraded") - before >= 2
    assert not eng.arena.kv_head_axis          # head axis replicated
    assert eng.arena.shard_shape("pool_k")[3] == 3
    assert _run(eng) == base


def test_validate_spec_divisible_vs_indivisible():
    from paddle_tpu.distributed.sharding_utils import validate_spec
    from paddle_tpu.serving.arena import KV_POOL_SPEC
    mesh = _mesh(2)
    ticks = []
    ok = validate_spec(KV_POOL_SPEC, (2, 8, 4, 4, 8), mesh,
                       on_fallback=ticks.append)
    assert tuple(ok) == tuple(KV_POOL_SPEC) and not ticks
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        bad = validate_spec(KV_POOL_SPEC, (2, 8, 4, 3, 8), mesh,
                            on_fallback=ticks.append)
    assert tuple(bad) == ()
    assert len(ticks) == 1 and "not divisible" in ticks[0]


def test_arena_degrade_counter_via_resolve_spec():
    from paddle_tpu.serving.arena import KV_POOL_SPEC, StateArena
    mesh = _mesh(2)
    arena = StateArena(mesh=mesh)
    before = counters.get("serving.mesh.spec_degraded")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        spec = arena.resolve_spec("pool_k", KV_POOL_SPEC, (2, 8, 4, 3, 8))
    assert tuple(spec) == ()
    assert counters.get("serving.mesh.spec_degraded") == before + 1


# ---------------------------------------------------------------------------
# arena program cache accounting
# ---------------------------------------------------------------------------

def test_arena_program_cache_lru_eviction_and_rebuild():
    from paddle_tpu.serving.arena import StateArena
    arena = StateArena(program_cache_cap=2)
    store = {}
    built = []

    def builder(key):
        def build():
            built.append(key)
            return f"prog-{key}"
        return build

    before = counters.snapshot()
    assert arena.program(store, "a", builder("a")) == "prog-a"
    assert arena.program(store, "b", builder("b")) == "prog-b"
    assert arena.program(store, "a", builder("a")) == "prog-a"  # hit
    assert arena.program(store, "c", builder("c")) == "prog-c"  # evicts b
    assert "b" not in store
    assert arena.program(store, "b", builder("b")) == "prog-b"  # rebuild
    d = counters.delta(before)
    assert built == ["a", "b", "c", "b"]
    assert d.get("serving.arena.program_hits", 0) == 1
    assert d.get("serving.arena.program_misses", 0) == 4
    assert d.get("serving.arena.program_evictions", 0) >= 1
    assert d.get("serving.arena.program_rebuilds", 0) == 1
    assert counters.get("serving.arena.programs") == 2


def test_arena_passthrough_without_mesh():
    import jax.numpy as jnp
    from paddle_tpu.serving.arena import KV_POOL_SPEC, StateArena
    arena = StateArena()
    v = arena.declare("pool_k", np.zeros((2, 8, 4, 4, 8), np.float32),
                      spec=KV_POOL_SPEC)
    assert isinstance(v, jnp.ndarray)
    assert not arena.kv_head_axis
    assert arena.tag == ""
    assert arena.expected_collectives is None
    tree = {"w": np.ones((4, 4), np.float32)}
    assert arena.declare_tree("weights", tree) is tree
