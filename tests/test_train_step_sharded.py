"""Multi-chip SPMD train step: mesh-native CompiledTrainStep contract.

Covers the mesh promotion of the device-resident train state (runs on the
forced 8-device CPU backend — see conftest.py):
  * mesh(1,1) is BIT-identical to the single-device path (the mesh
    machinery adds no numerics);
  * dp=2 gradient sync matches the single-device full-batch step (GSPMD
    gradient averaging is numerically invisible up to fp associativity);
  * shard_rules / parameter placements really shard the donated carry —
    params AND optimizer moments live as local shards, and donation still
    consumes the previous carry;
  * fused_steps=K on a mesh keeps the launch economics (one XLA dispatch
    per K-step window) and the single-step losses;
  * the steady-state counter gates (zero retraces / rehydrates / host
    binds) hold unchanged on the mesh path;
  * ``infer_partition_specs`` rule resolution (first match wins, soft
    fallback to replicated on invalid axes / indivisible dims);
  * the sharded prefetchers stage batches data-parallel in one sharded
    ``device_put`` with values bit-identical to the plain loader.
"""

import numpy as np
import pytest

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.jit as pjit
import paddle_tpu.nn as nn
from paddle_tpu.profiler import counters


def _mse(m, x, y):
    return ((m(x) - y) ** 2).mean()


def _mesh(*shape, axes=("dp", "mp")):
    need = int(np.prod(shape))
    if jax.device_count() < need:
        pytest.skip(f"needs {need} devices")
    return Mesh(np.array(jax.devices()[:need]).reshape(shape), axes)


def _make(mesh=None, rules=None, fused=1, scaler=None, opt_cls=None):
    paddle.seed(7)
    net = nn.Sequential(nn.Linear(8, 16), nn.GELU(), nn.Linear(16, 4))
    opt_cls = opt_cls or paddle.optimizer.AdamW
    opt = opt_cls(learning_rate=1e-2, parameters=net.parameters())
    step = pjit.CompiledTrainStep(net, _mse, opt, fused_steps=fused,
                                  mesh=mesh, shard_rules=rules,
                                  scaler=scaler)
    return net, opt, step


def _data(n=6, b=8):
    rng = np.random.RandomState(0)
    return ([rng.randn(b, 8).astype("float32") for _ in range(n)],
            [rng.randn(b, 4).astype("float32") for _ in range(n)])


def _run(step, xs, ys):
    return [float(step(paddle.to_tensor(x), paddle.to_tensor(y)).numpy())
            for x, y in zip(xs, ys)]


class TestMeshTrainStep:
    def test_mesh11_bit_identical_to_single_device(self):
        xs, ys = _data()
        _, _, s0 = _make()
        l0 = _run(s0, xs, ys)
        _, _, s1 = _make(mesh=_mesh(1, 1))
        assert _run(s1, xs, ys) == l0

    def test_dp2_matches_single_device(self):
        xs, ys = _data()
        _, _, s0 = _make()
        l0 = _run(s0, xs, ys)
        _, _, s2 = _make(mesh=_mesh(2, 1))
        l2 = _run(s2, xs, ys)
        # dp splits the batch; GSPMD averages the per-shard grads — only
        # fp summation order may differ
        assert np.allclose(l0, l2, rtol=1e-5, atol=1e-6)

    def test_dp2_gradient_sync_parity(self):
        # one optimizer step from identical init: dp=2 updated params must
        # match the single-device full-batch update (the gradient the
        # optimizer saw is the same mean over all rows)
        xs, ys = _data(n=1)
        _, _, s0 = _make()
        s0(paddle.to_tensor(xs[0]), paddle.to_tensor(ys[0])).numpy()
        _, _, s2 = _make(mesh=_mesh(2, 1))
        s2(paddle.to_tensor(xs[0]), paddle.to_tensor(ys[0])).numpy()
        p0 = {k: np.asarray(v) for k, v in s0._state[0].items()}
        p2 = {k: np.asarray(v) for k, v in s2._state[0].items()}
        assert p0.keys() == p2.keys()
        for k in p0:
            assert np.allclose(p0[k], p2[k], rtol=1e-5, atol=1e-6), k

    def test_rules_shard_params_and_optimizer_state(self):
        mesh = _mesh(2, 2)
        xs, ys = _data(n=2)
        _, _, step = _make(mesh=mesh,
                           rules=[(r"\.weight$", P(None, "mp"))])
        _run(step, xs, ys)
        w = step._state[0]["0.weight"]
        assert w.sharding.spec == P(None, "mp")
        # (8, 16) over mp=2 → (8, 8) local shards
        assert tuple(w.addressable_shards[0].data.shape) == (8, 8)
        # Adam moments inherit the param's spec (sharded state, not a
        # replicated shadow copy)
        m1 = step._state[2]["acc"]["moment1"]
        specs = {getattr(v.sharding, "spec", None)
                 for v in m1.values()
                 if hasattr(v, "sharding") and len(v.shape) == 2
                 and v.shape == (8, 16)}
        assert P(None, "mp") in specs

    def test_donation_consumes_previous_sharded_carry(self):
        xs, ys = _data(n=3)
        _, _, step = _make(mesh=_mesh(2, 1),
                           rules=[(r"\.weight$", P(None, "mp"))])
        step(paddle.to_tensor(xs[0]), paddle.to_tensor(ys[0])).numpy()
        step(paddle.to_tensor(xs[1]), paddle.to_tensor(ys[1])).numpy()
        held = step._state[0]["0.weight"]
        step(paddle.to_tensor(xs[2]), paddle.to_tensor(ys[2])).numpy()
        assert held.is_deleted()  # buffer was donated, not copied

    def test_steady_state_counters_on_mesh(self):
        xs, ys = _data()
        _, _, step = _make(mesh=_mesh(2, 1))
        _run(step, xs[:3], ys[:3])  # hydrate + both trace structures
        before = counters.snapshot()
        _run(step, xs[3:], ys[3:])
        d = counters.delta(before)
        assert d.get("jit.traces", 0) == 0
        assert d.get("jit.hydrates", 0) == 0
        assert d.get("jit.syncs", 0) == 0
        assert d.get("jit.host.bind_layer_state", 0) == 0
        assert d.get("jit.host.bind_optimizer_state", 0) == 0
        assert d.get("jit.host.dispatches", 0) == 3
        assert d.get("jit.cache_hits", 0) == 3
        # GSPMD collectives are compiled into the program, never
        # host-issued
        assert d.get("dist.collective_launches", 0) == 0

    def test_fused_on_mesh_bit_identical_and_one_dispatch(self):
        from paddle_tpu.io import Window
        mesh = _mesh(2, 1)
        xs, ys = _data(n=8)
        _, _, s1 = _make(mesh=mesh)
        l1 = _run(s1, xs, ys)
        _, _, s2 = _make(mesh=mesh, fused=2)

        def win(i):
            return Window((paddle.to_tensor(np.stack(xs[i:i + 2])),
                           paddle.to_tensor(np.stack(ys[i:i + 2]))), 2)

        l2 = []
        for i in range(0, 8, 2):
            l2.extend(float(v) for v in np.asarray(s2(win(i)).numpy()))
        assert l1 == l2
        before = counters.snapshot()
        s2(win(4)).numpy()
        d = counters.delta(before)
        assert d.get("jit.host.dispatches", 0) == 1
        assert d.get("jit.steps", 0) == 2
        assert d.get("jit.traces", 0) == 0

    def test_gradscaler_on_mesh_skips_same_steps(self):
        xs, ys = _data()
        xs_bad = [x.copy() for x in xs]
        xs_bad[2][0, 0] = np.inf

        def run(mesh):
            _, _, s = _make(
                mesh=mesh,
                scaler=paddle.amp.GradScaler(init_loss_scaling=2.0 ** 10))
            out = _run(s, xs_bad, ys)
            s.sync()
            return out

        l0, l2 = run(None), run(_mesh(2, 1))
        assert ([np.isfinite(v) for v in l0]
                == [np.isfinite(v) for v in l2])
        assert np.allclose([v for v in l0 if np.isfinite(v)],
                           [v for v in l2 if np.isfinite(v)], rtol=1e-5)

    def test_indivisible_batch_degrades_to_replicated(self):
        # 5 rows on dp=2: the batch constraint must not apply (5 % 2 != 0)
        # and the step still matches the single-device run
        rng = np.random.RandomState(3)
        x = rng.randn(5, 8).astype("float32")
        y = rng.randn(5, 4).astype("float32")
        _, _, s0 = _make()
        l0 = float(s0(paddle.to_tensor(x), paddle.to_tensor(y)).numpy())
        _, _, s2 = _make(mesh=_mesh(2, 1))
        l2 = float(s2(paddle.to_tensor(x), paddle.to_tensor(y)).numpy())
        assert np.allclose(l0, l2, rtol=1e-5, atol=1e-6)

    def test_gpt_placements_auto_pickup(self):
        # model-declared tensor-parallel placements (annotate_param) must
        # shard the carry with NO shard_rules passed
        from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                       GPTPretrainingCriterion)
        mesh = _mesh(1, 2)
        paddle.seed(0)
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                        num_heads=4, max_seq_len=16,
                        use_flash_attention=False)
        model = GPTForCausalLM(cfg)
        crit = GPTPretrainingCriterion()
        opt = paddle.optimizer.AdamW(1e-4, parameters=model.parameters())
        step = pjit.CompiledTrainStep(
            model, lambda m, x, l: crit(m(x), l), opt, mesh=mesh)
        ids = paddle.randint(0, cfg.vocab_size, [2, 16])
        labels = paddle.randint(0, cfg.vocab_size, [2, 16])
        assert np.isfinite(float(step(ids, labels).numpy()))
        mp_sharded = [k for k, v in step._state[0].items()
                      if "mp" in str(getattr(v.sharding, "spec", P()))]
        assert mp_sharded, "no parameter picked up an mp placement"


class TestInferPartitionSpecs:
    def _mesh22(self):
        return _mesh(2, 2)

    def test_first_matching_rule_wins(self):
        from paddle_tpu.distributed.sharding_utils import (
            infer_partition_specs)
        mesh = self._mesh22()
        tree = {"enc": {"weight": np.zeros((8, 16))},
                "dec": {"weight": np.zeros((16, 8))}}
        specs = infer_partition_specs(
            tree, mesh,
            [(r"enc/weight", P("mp", None)),
             (r"weight", P(None, "mp"))])
        assert specs["enc"]["weight"] == P("mp", None)
        assert specs["dec"]["weight"] == P(None, "mp")

    def test_unmatched_leaves_get_default(self):
        from paddle_tpu.distributed.sharding_utils import (
            infer_partition_specs)
        mesh = self._mesh22()
        tree = {"w": np.zeros((8, 8)), "b": np.zeros((8,))}
        specs = infer_partition_specs(tree, mesh,
                                      [(r"^w$", P("dp", None))])
        assert specs["w"] == P("dp", None)
        assert specs["b"] == P()
        none_specs = infer_partition_specs(
            tree, mesh, [(r"^w$", P("dp", None))], default=None)
        assert none_specs["b"] is None

    def test_unknown_axis_falls_back_replicated(self):
        from paddle_tpu.distributed.sharding_utils import (
            infer_partition_specs)
        mesh = self._mesh22()
        tree = {"w": np.zeros((8, 8))}
        with pytest.warns(RuntimeWarning, match="not in"):
            specs = infer_partition_specs(tree, mesh,
                                          [(r"w", P("fsdp", None))])
        assert specs["w"] == P()

    def test_indivisible_dim_falls_back_replicated(self):
        from paddle_tpu.distributed.sharding_utils import (
            infer_partition_specs)
        mesh = self._mesh22()
        tree = {"w": np.zeros((7, 8))}  # 7 % dp=2 != 0
        with pytest.warns(RuntimeWarning, match="not divisible"):
            specs = infer_partition_specs(tree, mesh,
                                          [(r"w", P("dp", None))])
        assert specs["w"] == P()

    def test_nested_paths_and_sequences(self):
        from paddle_tpu.distributed.sharding_utils import (
            infer_partition_specs)
        mesh = self._mesh22()
        tree = {"layers": [{"weight": np.zeros((4, 8))},
                           {"weight": np.zeros((4, 8))}]}
        specs = infer_partition_specs(
            tree, mesh, [(r"layers/1/weight", P(None, "mp"))])
        assert specs["layers"][0]["weight"] == P()
        assert specs["layers"][1]["weight"] == P(None, "mp")


class TestShardedPrefetchers:
    def _loader(self, n=8, b=4):
        from paddle_tpu.io import DataLoader, TensorDataset
        rng = np.random.RandomState(5)
        ds = TensorDataset(
            [paddle.to_tensor(rng.randn(n * b, 8).astype("float32")),
             paddle.to_tensor(rng.randn(n * b, 4).astype("float32"))])
        return DataLoader(ds, batch_size=b, shuffle=False)

    def test_device_prefetcher_sharded_values_identical(self):
        from paddle_tpu.io import DevicePrefetcher
        mesh = _mesh(2, 1)
        loader = self._loader()
        plain = [[np.asarray(t.numpy()) for t in batch]
                 for batch in loader]
        before = counters.snapshot()
        pref = DevicePrefetcher(loader,
                                sharding=NamedSharding(mesh, P("dp")))
        staged = list(pref)
        d = counters.delta(before)
        assert len(staged) == len(plain)
        for got, want in zip(staged, plain):
            for g, w in zip(got, want):
                assert np.array_equal(np.asarray(g.numpy()), w)
                # each leaf landed data-parallel in one sharded put
                assert g._data.sharding.spec == P("dp")
        assert d.get("dist.device_put_sharded_bytes", 0) > 0

    def test_device_prefetcher_indivisible_leaf_replicates(self):
        from paddle_tpu.io import DevicePrefetcher
        mesh = _mesh(2, 1)
        from paddle_tpu.io import DataLoader, TensorDataset
        rng = np.random.RandomState(5)
        ds = TensorDataset(
            [paddle.to_tensor(rng.randn(9, 8).astype("float32"))])
        loader = DataLoader(ds, batch_size=3, shuffle=False)  # 3 % 2 != 0
        pref = DevicePrefetcher(loader,
                                sharding=NamedSharding(mesh, P("dp")))
        for (t,) in pref:
            # degraded to replicated-on-mesh: uniform device set, no
            # partial shards
            assert t._data.sharding.spec == P()
            assert len(t._data.sharding.device_set) == 2

    def test_stacking_prefetcher_sharded_window(self):
        from paddle_tpu.io import StackingPrefetcher
        mesh = _mesh(2, 1)
        loader = self._loader(n=4, b=4)
        plain = [[np.asarray(t.numpy()) for t in batch]
                 for batch in loader]
        wins = list(StackingPrefetcher(
            loader, k=2, sharding=NamedSharding(mesh, P("dp"))))
        assert len(wins) == 2
        for wi, w in enumerate(wins):
            for leaf_i, leaf in enumerate(w):  # a Window IS the arg tuple
                # window axis replicated, batch axis sharded — the xs
                # layout the mesh-native fused step scans over
                assert leaf._data.sharding.spec == P(None, "dp")
                assert tuple(leaf._data.addressable_shards[0].data.shape
                             )[:2] == (2, 2)
                want = np.stack([plain[2 * wi + j][leaf_i]
                                 for j in range(2)])
                assert np.array_equal(np.asarray(leaf.numpy()), want)
