"""Static-analysis subsystem (paddle_tpu.analysis): two passes.

Pass 1 — the AOT program auditor proves compile-time invariants on the
actual jitted programs (donation aliasing, no host callbacks, static
shapes, dtype policy, collective census, HBM budget), hooked into
``jit.CompiledTrainStep`` and the serving engines behind
``FLAGS_program_audit``.  Pass 2 — the TPU-hazard linter (PT001-PT006)
gates the source tree against the idioms that cost a bench run to
discover dynamically.  Both must catch seeded violations AND pass clean
over the real train-step / serving programs — the same double gate
``scripts/check_counters.py`` enforces in CI."""

import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.jit as pjit
import paddle_tpu.nn as nn
from paddle_tpu.analysis import lint as ptlint
from paddle_tpu.analysis import program_audit as paudit
from paddle_tpu.core import flags as cflags
from paddle_tpu.profiler import counters

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def audit_mode():
    """Set FLAGS_program_audit for one test; restore 'off' + forget the
    audited-name dedupe set afterwards (process-global state)."""
    paudit.reset_audited()

    def _set(mode):
        cflags.set_flags({"FLAGS_program_audit": mode})

    try:
        yield _set
    finally:
        cflags.set_flags({"FLAGS_program_audit": "off"})
        paudit.reset_audited()


def _mse(m, x, y):
    return ((m(x) - y) ** 2).mean()


def _train_step(**kw):
    paddle.seed(7)
    net = nn.Linear(8, 4)
    opt = paddle.optimizer.Adam(parameters=net.parameters(),
                                learning_rate=1e-2)
    step = pjit.CompiledTrainStep(net, _mse, opt, **kw)
    x = paddle.randn([16, 8])
    y = paddle.randn([16, 4])
    return step, x, y


# ---------------------------------------------------------------------------
# linter: one positive + one suppressed case per rule
# ---------------------------------------------------------------------------

def _lint(src, **kw):
    kw.setdefault("check_counters", False)
    return ptlint.lint_source(src, path="paddle_tpu/fake.py", **kw)


def _active(src, **kw):
    return [f for f in _lint(src, **kw) if not f.suppressed]


class TestLintRules:
    def test_pt001_host_sync_in_traced(self):
        src = (
            "import jax\n"
            "@jax.jit\n"
            "def step(x):\n"
            "    v = x.mean().item()\n"
            "    return float(x.sum())\n")
        rules = [f.rule for f in _active(src)]
        assert rules.count("PT001") == 2

    def test_pt001_shape_reads_are_fine(self):
        src = (
            "import jax\n"
            "@jax.jit\n"
            "def step(x):\n"
            "    n = float(x.shape[0])\n"
            "    k = int(len(x))\n"
            "    return x / n * k\n")
        assert not _active(src)

    def test_pt001_transitive_callee(self):
        # helper called from a jitted fn is traced too
        src = (
            "import jax\n"
            "def helper(x):\n"
            "    return x.numpy()\n"
            "@jax.jit\n"
            "def step(x):\n"
            "    return helper(x)\n")
        assert [f.rule for f in _active(src)] == ["PT001"]

    def test_pt001_untraced_code_not_flagged(self):
        src = ("def host_fn(x):\n"
               "    return float(x.mean())\n")
        assert not _active(src)

    def test_pt002_compile_and_discard(self):
        src = ("import jax\n"
               "def f(g, x):\n"
               "    return jax.jit(g)(x)\n")
        assert [f.rule for f in _active(src)] == ["PT002"]

    def test_pt002_unhashable_cache_key(self):
        src = ("def lookup(self, shapes):\n"
               "    return self._jits[[s for s in shapes]]\n")
        assert [f.rule for f in _active(src)] == ["PT002"]

    def test_pt003_donation_ternary_trap(self):
        src = ("import jax\n"
               "def mk(fn, donate):\n"
               "    return jax.jit(fn,\n"
               "        donate_argnums=donate + (7,) if donate else ())\n")
        assert [f.rule for f in _active(src)] == ["PT003"]

    def test_pt003_parenthesized_fix_clean(self):
        # the shape the repo actually uses after the fix
        src = ("import jax\n"
               "def mk(fn, donate):\n"
               "    return jax.jit(fn,\n"
               "        donate_argnums=donate + ((7,) if donate else ()))\n")
        assert not _active(src)

    def test_pt003_plain_ternary_clean(self):
        # no binary operand in either branch — unambiguous, allowed
        src = ("import jax\n"
               "def mk(fn, flag):\n"
               "    return jax.jit(fn,\n"
               "        donate_argnums=(0, 1, 2) if flag else ())\n")
        assert not _active(src)

    def test_pt004_nondeterminism_in_traced(self):
        src = ("import jax, time\n"
               "import numpy as np\n"
               "@jax.jit\n"
               "def step(x):\n"
               "    t = time.time()\n"
               "    r = np.random.rand()\n"
               "    return x * t + r\n")
        rules = [f.rule for f in _active(src)]
        assert rules.count("PT004") == 2

    def test_pt005_dispatch_under_lock(self):
        src = ("import jax.numpy as jnp\n"
               "def run(self, x):\n"
               "    with self._lock:\n"
               "        dec = self._pdecode(1)\n"
               "        out = dec(x)\n"
               "        s = jnp.sum(out)\n"
               "    return s\n")
        rules = [f.rule for f in _active(src)]
        assert rules.count("PT005") == 2

    def test_pt005_dispatch_outside_lock_clean(self):
        src = ("import jax.numpy as jnp\n"
               "def run(self, x):\n"
               "    with self._lock:\n"
               "        dec = self._pdecode(1)\n"
               "    return jnp.sum(dec(x))\n")
        assert not _active(src)

    def test_pt006_undocumented_counter(self):
        pats = ptlint.documented_counter_patterns()
        src = ("from paddle_tpu.profiler import counters\n"
               "counters.inc('totally.bogus_name')\n"
               "counters.inc('jit.steps')\n"
               "counters.inc(f'dist.{op}')\n")
        active = _active(src, check_counters=True, counter_patterns=pats)
        assert [f.rule for f in active] == ["PT006"]
        assert "totally.bogus_name" in active[0].message

    def test_pt006_analysis_counters_documented(self):
        # the auditor's own counters must pass its own lint
        pats = ptlint.documented_counter_patterns()
        for name in ("analysis.audits", "analysis.findings",
                     "analysis.findings.donation-dropped",
                     "analysis.findings.host-callback"):
            assert ptlint._counter_name_ok(name, False, pats), name

    def test_suppression_with_reason(self):
        src = ("import jax\n"
               "@jax.jit\n"
               "def step(x):\n"
               "    # ptlint: disable=PT001 reason=\"test fixture\"\n"
               "    return x.numpy()\n")
        finds = _lint(src)
        assert len(finds) == 1 and finds[0].suppressed
        assert finds[0].reason == "test fixture"
        assert not _active(src)

    def test_suppression_without_reason_stays_active(self):
        src = ("import jax\n"
               "@jax.jit\n"
               "def step(x):\n"
               "    return x.numpy()  # ptlint: disable=PT001\n")
        assert [f.rule for f in _active(src)] == ["PT001"]

    def test_fingerprint_ignores_line_numbers(self):
        a = ptlint.LintFinding(rule="PT001", path="p.py", line=3, col=0,
                               message="m", snippet="return x.numpy()")
        b = ptlint.LintFinding(rule="PT001", path="p.py", line=99, col=4,
                               message="m", snippet="return x.numpy()")
        assert ptlint.fingerprint(a) == ptlint.fingerprint(b)


# ---------------------------------------------------------------------------
# linter: the repo itself must be clean vs the checked-in baseline
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def repo_findings():
    return ptlint.lint_paths(ptlint.default_targets(ROOT), root=ROOT)


class TestRepoSweep:
    def test_repo_has_no_new_findings(self, repo_findings):
        base = ptlint.load_baseline(
            os.path.join(ROOT, "scripts", "lint_baseline.json"))
        new = [f for f in repo_findings
               if not f.suppressed and ptlint.fingerprint(f) not in base]
        assert not new, "NEW lint findings:\n" + "\n".join(
            f.format() for f in new)

    def test_all_suppressions_carry_reasons(self, repo_findings):
        for f in repo_findings:
            if f.suppressed:
                assert f.reason, f.format()

    @pytest.mark.slow
    def test_lint_cli_check_exits_zero(self):
        env = dict(os.environ, PYTHONPATH=ROOT, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, os.path.join(ROOT, "scripts", "lint_tpu.py"),
             "--check"],
            capture_output=True, text=True, env=env, timeout=240)
        assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# auditor: seeded broken fixtures must be caught by the right rule
# ---------------------------------------------------------------------------

class TestAuditorFixtures:
    def test_host_callback_caught(self):
        def f(x):
            return jax.pure_callback(
                lambda a: np.asarray(a),
                jax.ShapeDtypeStruct(x.shape, x.dtype), x)

        rep = paudit.audit_program("t.cb", jax.jit(f), jnp.ones((4,)),
                                   compile_program=False)
        assert not rep.ok
        assert {f.rule for f in rep.findings} == {"host-callback"}
        assert rep.primitive_counts.get("pure_callback", 0) >= 1

    def test_dropped_donation_caught(self):
        # sum() consumes the donated buffer without any same-shaped
        # output to alias it to — the drop must be a hard finding
        fn = jax.jit(lambda a: jnp.sum(a), donate_argnums=(0,))
        rep = paudit.audit_program("t.drop", fn, jnp.ones((4, 4)),
                                   donate_argnums=(0,),
                                   compile_program=False)
        assert any(f.rule == "donation-dropped" for f in rep.findings)
        assert rep.donated_leaves == 1 and rep.aliased_leaves == 0

    def test_dynamic_shape_caught(self):
        from jax import export as jexport
        bdim = jexport.symbolic_shape("b, 4")
        rep = paudit.audit_program(
            "t.dyn", jax.jit(lambda z: z * 2),
            jax.ShapeDtypeStruct(bdim, jnp.float32),
            compile_program=False)
        assert any(f.rule == "dynamic-shape" for f in rep.findings)

    def test_f64_promotion_caught(self):
        jax.config.update("jax_enable_x64", True)
        try:
            rep = paudit.audit_program(
                "t.f64", jax.jit(lambda x: x * 2.0),
                jnp.ones((4,), jnp.float64), compile_program=False)
        finally:
            jax.config.update("jax_enable_x64", False)
        assert any(f.rule == "f64-promotion" for f in rep.findings)

    def test_collective_census_caught(self):
        from jax.experimental.shard_map import shard_map
        from jax.sharding import Mesh, PartitionSpec as P
        mesh = Mesh(np.array(jax.devices()[:2]), ("i",))
        fn = jax.jit(shard_map(lambda x: jax.lax.psum(x, "i"), mesh=mesh,
                               in_specs=P("i"), out_specs=P()))
        rep = paudit.audit_program("t.coll", fn, jnp.ones((2,)),
                                   expect_no_collectives=True,
                                   compile_program=False)
        assert any(f.rule == "collective-budget" for f in rep.findings)
        assert rep.collective_counts.get("psum2", 0) >= 1
        # mesh programs with collectives *allowed* report the census only
        rep2 = paudit.audit_program("t.coll.ok", fn, jnp.ones((2,)),
                                    expect_no_collectives=False,
                                    compile_program=False)
        assert rep2.ok and rep2.collective_counts.get("psum2", 0) >= 1

    def test_hbm_budget_caught(self):
        fn = jax.jit(lambda x: x @ x)
        rep = paudit.audit_program("t.hbm", fn, jnp.ones((64, 64)),
                                   hbm_budget_bytes=1)
        assert any(f.rule == "hbm-budget" for f in rep.findings)

    def test_counters_and_flight_fed(self, audit_mode):
        before = counters.snapshot()
        fn = jax.jit(lambda a: jnp.sum(a), donate_argnums=(0,))
        paudit.audit_program("t.counted", fn, jnp.ones((4, 4)),
                             donate_argnums=(0,), compile_program=False)
        d = counters.delta(before)
        assert d.get("analysis.audits") == 1
        assert d.get("analysis.findings.donation-dropped") == 1


# ---------------------------------------------------------------------------
# auditor: the real programs must pass clean (the double gate)
# ---------------------------------------------------------------------------

class TestAuditorCleanPrograms:
    def test_train_step_clean_under_enforce(self, audit_mode):
        audit_mode("enforce")
        step, x, y = _train_step(metrics=True)
        before = counters.snapshot()
        step(x, y)  # fresh compile -> audit at the compile site; must not raise
        d = counters.delta(before)
        assert d.get("analysis.audits", 0) >= 1
        assert d.get("analysis.findings", 0) == 0
        # dedupe: steady-state steps never re-audit
        before = counters.snapshot()
        step(x, y)
        assert counters.delta(before).get("analysis.audits", 0) == 0

    def test_fused_window_clean_under_enforce(self, audit_mode):
        audit_mode("enforce")
        from paddle_tpu.io import StackingPrefetcher
        step, x, y = _train_step(metrics=True, fused_steps=2)
        before = counters.snapshot()
        # window 1 falls back to single-step (accumulators not yet
        # materialized); window 2 compiles + audits the fused program
        for w in StackingPrefetcher(iter([(x, y)] * 4), k=2):
            step(*w)
        d = counters.delta(before)
        assert d.get("jit.fused_windows", 0) >= 1
        assert d.get("analysis.audits", 0) >= 2  # step + window programs
        assert d.get("analysis.findings", 0) == 0

    def test_serving_programs_clean_under_enforce(self, audit_mode):
        audit_mode("enforce")
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        from paddle_tpu.serving import LLMEngine
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=4, max_seq_len=32,
                        use_flash_attention=False)
        paddle.seed(31)
        m = GPTForCausalLM(cfg)
        m.eval()
        eng = LLMEngine(m, max_slots=2, max_seq_len=32, min_bucket=4)
        before = counters.snapshot()
        outs = eng.generate([[1, 2, 3], [4, 5]], max_new_tokens=4)
        d = counters.delta(before)
        assert len(outs) == 2
        assert d.get("analysis.audits", 0) >= 2  # prefill + decode at least
        assert d.get("analysis.findings", 0) == 0


# ---------------------------------------------------------------------------
# donation regression: the macc buffer must alias whenever the carry donates
# (the PT003 ternary at the _make_jit sites used to make this easy to lose)
# ---------------------------------------------------------------------------

class TestMaccDonation:
    def _compiled_step_args(self, **kw):
        step, x, y = _train_step(metrics=True, **kw)
        step(x, y)
        params, buffers, opt_state, sstate, rng_key = step._state
        cargs = (params, buffers, opt_state, step._lr_dev, rng_key, sstate,
                 step._strip((x, y)), step._macc)
        return step, cargs

    def test_step_macc_aliased_when_carry_donated(self):
        step, cargs = self._compiled_step_args()
        jit_fn = step._jits[(False, True)]
        # the macc dict is arg 7: all 4 of its leaves must alias outputs
        rep = paudit.audit_program("t.macc", jit_fn, *cargs,
                                   donate_argnums=(7,),
                                   compile_program=False)
        assert rep.ok, [f.message for f in rep.findings]
        assert rep.donated_leaves == len(step._MACC_KEYS) == 4
        # and the full carry (params/buffers/opt-state) + macc donation holds
        rep = paudit.audit_program("t.macc.full", jit_fn, *cargs,
                                   donate_argnums=(0, 1, 2, 7),
                                   compile_program=False)
        assert rep.ok, [f.message for f in rep.findings]
        assert rep.aliased_leaves >= rep.donated_leaves > 4

    def test_window_macc_aliased_when_carry_donated(self, audit_mode):
        # the fused-window program audits (0,1,2,7) at its compile site;
        # enforce mode turns any dropped macc leaf into a raise here
        audit_mode("enforce")
        from paddle_tpu.io import StackingPrefetcher
        step, x, y = _train_step(metrics=True, fused_steps=2)
        before = counters.snapshot()
        for w in StackingPrefetcher(iter([(x, y)] * 4), k=2):
            step(*w)
        assert (False, 2, True) in step._fused_jits
        with paudit._AUDITED_LOCK:
            audited = set(paudit._AUDITED)
        assert "jit.window[check=0,k=2,metrics=1]" in audited
        assert counters.delta(before).get(
            "analysis.findings.donation-dropped", 0) == 0

    def test_no_aliasing_without_donation(self):
        step, cargs = self._compiled_step_args(donate=False)
        jit_fn = step._jits[(False, True)]
        txt = jit_fn.trace(*cargs).lower().as_text()
        aliased, total = paudit._aliased_arg_indices(txt)
        assert aliased == set()
        assert total == sum(len(jax.tree_util.tree_leaves(a))
                            for a in cargs)


# ---------------------------------------------------------------------------
# maybe_audit: flag modes + once-per-program dedupe
# ---------------------------------------------------------------------------

class TestMaybeAudit:
    BROKEN = staticmethod(
        lambda: jax.jit(lambda a: jnp.sum(a), donate_argnums=(0,)))

    def test_off_is_noop(self, audit_mode):
        audit_mode("off")
        before = counters.snapshot()
        out = paudit.maybe_audit("t.off", self.BROKEN(), jnp.ones((4, 4)),
                                 donate_argnums=(0,), compile_program=False)
        assert out is None
        assert counters.delta(before).get("analysis.audits", 0) == 0

    def test_warn_files_findings_without_raising(self, audit_mode):
        audit_mode("warn")
        before = counters.snapshot()
        rep = paudit.maybe_audit("t.warn", self.BROKEN(), jnp.ones((4, 4)),
                                 donate_argnums=(0,), compile_program=False)
        assert rep is not None and not rep.ok
        d = counters.delta(before)
        assert d.get("analysis.findings.donation-dropped") == 1

    def test_enforce_raises_at_compile_site(self, audit_mode):
        audit_mode("enforce")
        with pytest.raises(paudit.ProgramAuditError) as ei:
            paudit.maybe_audit("t.enforce", self.BROKEN(), jnp.ones((4, 4)),
                               donate_argnums=(0,), compile_program=False)
        assert "donation-dropped" in str(ei.value)
        assert ei.value.report.name == "t.enforce"

    def test_each_name_audited_once(self, audit_mode):
        audit_mode("warn")
        fn = jax.jit(lambda x: x + 1)
        before = counters.snapshot()
        first = paudit.maybe_audit("t.once", fn, jnp.ones((2,)),
                                   compile_program=False)
        second = paudit.maybe_audit("t.once", fn, jnp.ones((2,)),
                                    compile_program=False)
        assert first is not None and second is None
        assert counters.delta(before).get("analysis.audits") == 1

    def test_package_export(self):
        assert paddle.analysis.lint is ptlint
        assert paddle.analysis.program_audit is paudit
