"""Quantization: observers, fake-quant STE, and int8 weight-only PTQ.

Contracts: (1) observers track the right statistic (absmax running max;
percentile clips outliers below the absmax); (2) ``fake_quant`` is a
straight-through estimator — values snap to the 8-bit grid forward,
gradients pass through untouched; (3) ``channel_scales`` /
``quantize_weight_int8`` produce per-output-channel ``[L, 1, out]``
scales whose roundtrip error is bounded by half a quantization step;
(4) ``ptq_int8_decode_state`` swaps exactly the stacked matmul weights
for int8+scale pairs and the quantized serving logits stay within the
documented tolerance of fp32 on the tiny GPT.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.quantization import (PTQ_WEIGHTS, AbsmaxObserver,
                                     PercentileObserver, channel_scales,
                                     fake_quant, ptq_int8_decode_state,
                                     quantize_weight_int8)

_MODEL = None


def _model():
    global _MODEL
    if _MODEL is None:
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=4, max_seq_len=32,
                        use_flash_attention=False)
        paddle.seed(55)
        _MODEL = GPTForCausalLM(cfg)
        _MODEL.eval()
    return _MODEL


class TestObservers:
    def test_absmax_tracks_running_max(self):
        obs = AbsmaxObserver()
        obs(paddle.to_tensor(np.asarray([1.0, -3.0], np.float32)))
        assert float(obs.scales().numpy()) == 3.0
        obs(paddle.to_tensor(np.asarray([0.5], np.float32)))
        assert float(obs.scales().numpy()) == 3.0      # max never decays
        obs(paddle.to_tensor(np.asarray([-7.0], np.float32)))
        assert float(obs.scales().numpy()) == 7.0

    def test_percentile_clips_outliers(self):
        rng = np.random.default_rng(0)
        x = rng.standard_normal(4096).astype(np.float32)
        x[0] = 1000.0                                   # one outlier
        t = paddle.to_tensor(x)
        a, p = AbsmaxObserver(), PercentileObserver(percentile=99.0)
        a(t)
        p(t)
        assert float(a.scales().numpy()) == 1000.0      # absmax blown up
        assert float(p.scales().numpy()) < 5.0          # percentile is not

    def test_percentile_validation(self):
        with pytest.raises(ValueError, match="percentile"):
            PercentileObserver(percentile=0.0)
        with pytest.raises(ValueError, match="percentile"):
            PercentileObserver(percentile=101.0)


class TestFakeQuant:
    def test_forward_snaps_to_grid(self):
        x = np.linspace(-2.0, 2.0, 9).astype(np.float32)
        s = np.asarray(1.5, np.float32)
        y = fake_quant(paddle.to_tensor(x), paddle.to_tensor(s)).numpy()
        ref = np.round(np.clip(x / 1.5 * 127, -127, 127)) * 1.5 / 127
        assert np.allclose(np.asarray(y), ref, atol=1e-6)

    def test_straight_through_gradient(self):
        x = paddle.to_tensor(np.linspace(-1.0, 1.0, 8).astype(np.float32),
                             stop_gradient=False)
        s = paddle.to_tensor(np.asarray(1.0, np.float32))
        fake_quant(x, s).sum().backward()
        # STE: d(fake_quant)/dx == 1 everywhere inside the clip range
        assert np.allclose(np.asarray(x.grad.numpy()), np.ones(8))


class TestChannelScales:
    def test_shapes_and_absmax_values(self):
        rng = np.random.default_rng(1)
        w = rng.standard_normal((3, 16, 8)).astype(np.float32)
        s = np.asarray(channel_scales(w))
        assert s.shape == (3, 1, 8) and s.dtype == np.float32
        expect = np.abs(w).max(axis=1, keepdims=True) / 127.0
        assert np.allclose(s, expect, atol=1e-7)

    def test_percentile_observer_below_absmax_on_outliers(self):
        rng = np.random.default_rng(2)
        w = rng.standard_normal((2, 256, 4)).astype(np.float32)
        w[:, 0, :] = 50.0                               # outlier row
        sa = np.asarray(channel_scales(w, observer="absmax"))
        sp = np.asarray(channel_scales(w, observer="percentile",
                                       percentile=99.0))
        assert np.all(sp < sa)

    def test_invalid_observer_raises(self):
        with pytest.raises(ValueError, match="observer"):
            channel_scales(np.zeros((1, 2, 2), np.float32), observer="kl")

    def test_quantize_roundtrip_bound(self):
        rng = np.random.default_rng(3)
        w = (rng.standard_normal((2, 32, 16)) * 0.3).astype(np.float32)
        q, s = quantize_weight_int8(w)
        assert np.asarray(q).dtype == np.int8
        dq = np.asarray(q, np.float32) * np.asarray(s)
        # symmetric rounding: per-element error <= scale / 2
        assert np.all(np.abs(dq - w) <= np.asarray(s) / 2 + 1e-7)


class TestPTQDecodeState:
    def test_swaps_exactly_the_matmul_weights(self):
        m = _model()
        w = ptq_int8_decode_state(m)
        raw = m.decode_state()
        for name in PTQ_WEIGHTS:
            assert np.asarray(w["lws"][name]).dtype == np.int8
            scale = np.asarray(w["lws"][name + "__scale"])
            L, _, out = raw["lws"][name].shape
            assert scale.shape == (L, 1, out)
        # everything else untouched (embeddings, biases, norms, head)
        assert w["wte"] is raw["wte"] or np.array_equal(
            np.asarray(w["wte"]), np.asarray(raw["wte"]))
        for name in ("qkv_b", "proj_b", "fc1_b", "fc2_b", "ln1_w", "ln2_w"):
            if name in raw["lws"]:
                assert np.asarray(w["lws"][name]).dtype != np.int8

    def test_logit_tolerance_vs_fp32(self):
        # the documented PTQ gate: max |logit drift| <= 5% of the fp32
        # logit magnitude on the tiny model (same gate check_counters
        # enforces)
        import jax.numpy as jnp
        m = _model()
        w_fp = m.decode_state()
        w_q = ptq_int8_decode_state(m)
        ids = jnp.asarray(np.arange(16)[None, :] % 64, jnp.int32)
        _, _, ref = m.prefill_slot(w_fp, ids, 16)
        _, _, got = m.prefill_slot(w_q, ids, 16)
        ref, got = np.asarray(ref), np.asarray(got)
        drift = np.abs(got - ref).max()
        assert drift <= 0.05 * np.abs(ref).max(), drift

    def test_percentile_variant_also_within_tolerance(self):
        import jax.numpy as jnp
        m = _model()
        w_fp = m.decode_state()
        w_q = ptq_int8_decode_state(m, observer="percentile",
                                    percentile=99.9)
        ids = jnp.asarray(np.arange(12)[None, :] % 64, jnp.int32)
        _, _, ref = m.prefill_slot(w_fp, ids, 12)
        _, _, got = m.prefill_slot(w_q, ids, 12)
        ref, got = np.asarray(ref), np.asarray(got)
        assert np.abs(got - ref).max() <= 0.05 * np.abs(ref).max()
