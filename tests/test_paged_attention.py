"""Fused paged-attention decode kernel + quantized KV arena.

Contracts: (1) the Pallas kernel (interpret mode on CPU) matches a
materialized gather-softmax reference to float epsilon — the block-table
walk and online softmax are invisible in the math; (2) int8/fp8 pools
dequantized in-register match the explicitly dequantized reference
exactly (same fp32 ops, reordered by a commuting per-token scale);
(3) engines running kv_dtype / FLAGS_paged_kernel=pallas / weight-only
PTQ stay token-identical to the plain-XLA bf16 baseline on the tiny
model; (4) the shared ``kernels._shapes`` preflight validators fail
loudly, naming the offending dimension.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core.flags import flag, set_flags
from paddle_tpu.kernels import paged_attention as pa
from paddle_tpu.kernels._shapes import (LANE, NEG_INF, check_divides,
                                        check_equal, check_min_tile,
                                        min_sublane, neg_inf)

_MODEL = None


def _model():
    global _MODEL
    if _MODEL is None:
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=4, max_seq_len=32,
                        use_flash_attention=False)
        paddle.seed(77)
        _MODEL = GPTForCausalLM(cfg)
        _MODEL.eval()
    return _MODEL


def _paged(m, **kw):
    from paddle_tpu.serving import LLMEngine
    kw.setdefault("max_slots", 3)
    kw.setdefault("max_seq_len", 32)
    # min_bucket == prefill_chunk keeps every chunk in ONE bucket, so each
    # engine config compiles a single prefill program (suite-time budget).
    kw.setdefault("min_bucket", 8)
    kw.setdefault("block_size", 4)
    kw.setdefault("prefill_chunk", 8)
    return LLMEngine(m, kv_layout="paged", **kw)


def _run(eng, handles, limit=300):
    n = 0
    while not all(h.is_finished for h in handles):
        eng.step()
        n += 1
        assert n < limit, "engine did not converge"
    return n


@pytest.fixture()
def interpret_mode():
    pa._INTERPRET[0] = True
    yield
    pa._INTERPRET[0] = False


@pytest.fixture()
def pallas_mode(interpret_mode):
    set_flags({"FLAGS_paged_kernel": "pallas"})
    yield
    set_flags({"FLAGS_paged_kernel": "off"})


def _ref_paged(q, pool_k, pool_v, bt, pos, scale, sk=None, sv=None):
    """Materialized gather + softmax reference (the XLA-twin math in
    numpy): pool[bt] -> [B, S, nh, hd], causal-mask to pos, softmax."""
    B, nh, hd = q.shape
    bs = pool_k.shape[1]
    S = bt.shape[1] * bs
    k = pool_k[bt].reshape(B, S, nh, hd).astype(np.float32)
    v = pool_v[bt].reshape(B, S, nh, hd).astype(np.float32)
    if sk is not None:
        k = k * sk[bt].reshape(B, S)[:, :, None, None]
        v = v * sv[bt].reshape(B, S)[:, :, None, None]
    logits = np.einsum("bhd,bshd->bhs", q.astype(np.float32), k) * scale
    live = (np.arange(S)[None, :] <= pos[:, None])[:, None, :]
    logits = np.where(live, logits, NEG_INF)
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return np.einsum("bhs,bshd->bhd", p, v)


def _random_case(rng, B=3, nh=2, hd=8, n_blocks=16, bs=4, max_blocks=5,
                 dtype=np.float32):
    q = rng.standard_normal((B, nh, hd)).astype(dtype)
    pool_k = rng.standard_normal((n_blocks, bs, nh, hd)).astype(dtype)
    pool_v = rng.standard_normal((n_blocks, bs, nh, hd)).astype(dtype)
    # distinct physical blocks per row, deliberately out of order
    perm = rng.permutation(n_blocks)[:B * max_blocks]
    bt = perm.reshape(B, max_blocks).astype(np.int32)
    pos = rng.integers(0, max_blocks * bs, size=B).astype(np.int32)
    return q, pool_k, pool_v, bt, pos


class TestPagedDecodeKernel:
    def test_matches_gather_reference(self, interpret_mode):
        import jax.numpy as jnp
        rng = np.random.default_rng(0)
        q, pk, pv, bt, pos = _random_case(rng)
        scale = 1.0 / np.sqrt(q.shape[-1])
        out = np.asarray(pa.paged_decode_attention(
            jnp.asarray(q), jnp.asarray(pk), jnp.asarray(pv),
            jnp.asarray(bt), jnp.asarray(pos), scale=scale))
        ref = _ref_paged(q, pk, pv, bt, pos, scale)
        assert np.allclose(out, ref, atol=1e-5), np.abs(out - ref).max()

    def test_single_live_token(self, interpret_mode):
        # pos=0: only one key is live; attention must return exactly v[0]
        import jax.numpy as jnp
        rng = np.random.default_rng(1)
        q, pk, pv, bt, pos = _random_case(rng, B=2)
        pos[:] = 0
        out = np.asarray(pa.paged_decode_attention(
            jnp.asarray(q), jnp.asarray(pk), jnp.asarray(pv),
            jnp.asarray(bt), jnp.asarray(pos), scale=0.5))
        ref = pv[bt[:, 0], 0]                       # [B, nh, hd]
        assert np.allclose(out, ref, atol=1e-6)

    def test_bf16_pool(self, interpret_mode):
        import jax.numpy as jnp
        rng = np.random.default_rng(2)
        q, pk, pv, bt, pos = _random_case(rng)
        scale = 0.35
        out = np.asarray(pa.paged_decode_attention(
            jnp.asarray(q, jnp.bfloat16), jnp.asarray(pk, jnp.bfloat16),
            jnp.asarray(pv, jnp.bfloat16), jnp.asarray(bt),
            jnp.asarray(pos), scale=scale))
        ref = _ref_paged(
            np.asarray(jnp.asarray(q, jnp.bfloat16), np.float32),
            np.asarray(jnp.asarray(pk, jnp.bfloat16), np.float32),
            np.asarray(jnp.asarray(pv, jnp.bfloat16), np.float32),
            bt, pos, scale)
        assert np.allclose(out, ref, atol=2e-2), np.abs(out - ref).max()

    @pytest.mark.parametrize("kv_dtype", ["int8", "fp8"])
    def test_quantized_pool_matches_dequantized_reference(
            self, interpret_mode, kv_dtype):
        import jax.numpy as jnp
        rng = np.random.default_rng(3)
        q, pk, pv, bt, pos = _random_case(rng)
        qk, sk = pa.quantize_kv(jnp.asarray(pk), kv_dtype)
        qv, sv = pa.quantize_kv(jnp.asarray(pv), kv_dtype)
        scale = 1.0 / np.sqrt(q.shape[-1])
        out = np.asarray(pa.paged_decode_attention(
            jnp.asarray(q), qk, qv, jnp.asarray(bt), jnp.asarray(pos),
            sk, sv, scale=scale))
        # in-register dequant must equal the explicitly dequantized pool
        dk = np.asarray(pa.dequantize_kv(qk, sk))
        dv = np.asarray(pa.dequantize_kv(qv, sv))
        ref = _ref_paged(q, dk, dv, bt, pos, scale)
        assert np.allclose(out, ref, atol=1e-5), np.abs(out - ref).max()
        # and stay near the unquantized fp32 answer
        full = _ref_paged(q, pk, pv, bt, pos, scale)
        tol = 0.05 if kv_dtype == "int8" else 0.2
        assert np.abs(out - full).max() <= tol

    def test_jit_with_donated_pools(self, interpret_mode):
        import jax
        import jax.numpy as jnp
        rng = np.random.default_rng(4)
        q, pk, pv, bt, pos = _random_case(rng, B=2, max_blocks=3,
                                          n_blocks=8)

        @jax.jit
        def step(q, pk, pv, bt, pos):
            return pa.paged_decode_attention(q, pk, pv, bt, pos, scale=0.5)

        out = np.asarray(step(jnp.asarray(q), jnp.asarray(pk),
                              jnp.asarray(pv), jnp.asarray(bt),
                              jnp.asarray(pos)))
        ref = _ref_paged(q, pk, pv, bt, pos, 0.5)
        assert np.allclose(out, ref, atol=1e-5)

    def test_shape_mismatch_fails_preflight(self, interpret_mode):
        import jax.numpy as jnp
        rng = np.random.default_rng(5)
        q, pk, pv, bt, pos = _random_case(rng)
        with pytest.raises(ValueError, match="table_rows"):
            pa.paged_decode_attention(
                jnp.asarray(q), jnp.asarray(pk), jnp.asarray(pv),
                jnp.asarray(bt[:-1]), jnp.asarray(pos), scale=0.5)


class TestQuantizeKV:
    @pytest.mark.parametrize("kv_dtype", ["int8", "fp8"])
    def test_roundtrip(self, kv_dtype):
        import jax.numpy as jnp
        rng = np.random.default_rng(6)
        x = rng.standard_normal((5, 4, 2, 8)).astype(np.float32) * 3.0
        q, s = pa.quantize_kv(jnp.asarray(x), kv_dtype)
        assert q.dtype == pa.KV_DTYPES[kv_dtype]
        assert s.shape == (5, 4) and s.dtype == jnp.float32
        dq = np.asarray(pa.dequantize_kv(q, s))
        amax = np.abs(x).max(axis=(-2, -1), keepdims=True)
        if kv_dtype == "int8":
            # uniform grid: per-element error <= half a step of the
            # per-token absmax scale
            bound = amax / pa.KV_QMAX[kv_dtype] * 0.5
        else:
            # fp8-e4m3 is floating point: 3 mantissa bits -> relative
            # half-ulp error 2^-4, plus a denormal floor near zero
            bound = np.abs(x) * 2.0 ** -4 + amax / pa.KV_QMAX[kv_dtype]
        assert np.all(np.abs(dq - x) <= bound + 1e-6)

    def test_zero_token_quantizes_to_zero(self):
        import jax.numpy as jnp
        x = jnp.zeros((3, 2, 4))
        q, s = pa.quantize_kv(x, "int8")
        assert np.all(np.asarray(q) == 0)
        assert np.all(np.asarray(pa.dequantize_kv(q, s)) == 0)

    def test_kv_dtype_of(self):
        import jax.numpy as jnp
        assert pa.kv_dtype_of(jnp.int8) == "int8"
        assert pa.kv_dtype_of(jnp.float8_e4m3fn) == "fp8"
        assert pa.kv_dtype_of(jnp.bfloat16) is None
        assert pa.kv_dtype_of(jnp.float32) is None


class TestKernelMode:
    def test_default_off(self):
        assert flag("FLAGS_paged_kernel") == "off"
        assert pa.kernel_mode() == "off"

    def test_pallas_falls_back_off_tpu(self):
        set_flags({"FLAGS_paged_kernel": "pallas"})
        try:
            if pa._on_tpu():
                assert pa.kernel_mode() == "pallas"
            else:
                assert pa.kernel_mode() == "off"     # no TPU, no interpret
                pa._INTERPRET[0] = True
                assert pa.kernel_mode() == "pallas"  # tests force interpret
        finally:
            pa._INTERPRET[0] = False
            set_flags({"FLAGS_paged_kernel": "off"})

    def test_invalid_mode_raises(self):
        set_flags({"FLAGS_paged_kernel": "cuda"})
        try:
            with pytest.raises(ValueError, match="FLAGS_paged_kernel"):
                pa.kernel_mode()
        finally:
            set_flags({"FLAGS_paged_kernel": "off"})


class TestShapesPreflight:
    def test_check_divides_names_offender(self):
        check_divides("k", seq=(256, 128))           # fine
        with pytest.raises(ValueError) as ei:
            check_divides("flash_attention_fwd", heads=(2, 2),
                          seq_len_q=(100, 64))
        msg = str(ei.value)
        assert "flash_attention_fwd" in msg and "seq_len_q" in msg
        assert "ragged tail" in msg
        with pytest.raises(ValueError, match="must be >= 1"):
            check_divides("k", seq=(256, 0))

    def test_check_equal_names_offender(self):
        check_equal("k", rows=(3, 3))
        with pytest.raises(ValueError) as ei:
            check_equal("paged_attention", table_rows=(2, 3))
        assert "paged_attention" in str(ei.value)
        assert "table_rows" in str(ei.value)

    def test_check_min_tile(self):
        import jax.numpy as jnp
        check_min_tile("k", jnp.float32, sublane=8, lane=LANE)
        with pytest.raises(ValueError, match="lane"):
            check_min_tile("k", jnp.float32, lane=100)
        with pytest.raises(ValueError, match="sublane"):
            check_min_tile("k", jnp.bfloat16, sublane=8)   # bf16 needs 16
        assert min_sublane(jnp.float32) == 8
        assert min_sublane(jnp.bfloat16) == 16
        assert min_sublane(jnp.int8) == 32

    def test_neg_inf_is_finite_and_underflows(self):
        import jax.numpy as jnp
        assert NEG_INF == float(jnp.finfo(jnp.float32).min)
        assert np.isfinite(NEG_INF)
        assert np.isfinite(neg_inf(jnp.bfloat16))
        # the property the mask fill relies on: exp underflows to exactly 0
        assert np.exp(np.float32(NEG_INF)) == 0.0

    def test_neg_inf_softmax_parity_with_legacy_fill(self):
        # swapping -1e30 for finfo.min must not change any masked softmax
        rng = np.random.default_rng(7)
        logits = rng.standard_normal((4, 16)).astype(np.float32)
        mask = rng.random((4, 16)) < 0.5
        mask[:, 0] = True                            # keep one live key

        def sm(fill):
            z = np.where(mask, logits, fill)
            p = np.exp(z - z.max(-1, keepdims=True))
            return p / p.sum(-1, keepdims=True)

        assert np.array_equal(sm(np.float32(-1e30)), sm(np.float32(NEG_INF)))


class TestQuantizedEngines:
    def _baseline(self, m, prompts, seeds, max_new=6, **kw):
        eng = _paged(m)
        hs = [eng.add_request(p, max_new_tokens=max_new, seed=s, **kw)
              for p, s in zip(prompts, seeds)]
        _run(eng, hs)
        return [h.tokens for h in hs]

    def _prompts(self, seed=20):
        rng = np.random.default_rng(seed)
        return [rng.integers(0, 64, size=n).tolist() for n in (5, 9, 3)]

    # int8 engine identity also rides in the cheaper COW/counters tests
    # below and is gated end-to-end by scripts/check_counters.py; keep
    # only the fp8 variant in the tier-1 time budget.
    @pytest.mark.parametrize(
        "kv_dtype",
        [pytest.param("int8", marks=pytest.mark.slow), "fp8"])
    def test_kv_dtype_token_identity(self, kv_dtype):
        m = _model()
        prompts, seeds = self._prompts(), [0, 1, 2]
        refs = self._baseline(m, prompts, seeds)
        eng = _paged(m, kv_dtype=kv_dtype)
        assert eng.stats()["kv_dtype"] == kv_dtype
        hs = [eng.add_request(p, max_new_tokens=6, seed=s)
              for p, s in zip(prompts, seeds)]
        _run(eng, hs)
        for h, r in zip(hs, refs):
            assert h.tokens == r

    def test_pallas_greedy_and_sampled_identity(self, pallas_mode):
        m = _model()
        prompts, seeds = self._prompts(21), [3, 4, 5]
        kw = dict(do_sample=True, temperature=0.9, top_k=8)
        set_flags({"FLAGS_paged_kernel": "off"})
        greedy_ref = self._baseline(m, prompts, seeds)
        sampled_ref = self._baseline(m, prompts, seeds, **kw)
        set_flags({"FLAGS_paged_kernel": "pallas"})
        eng = _paged(m)
        assert eng.stats()["kv_kernel"] == "pallas"
        hs = [eng.add_request(p, max_new_tokens=6, seed=s)
              for p, s in zip(prompts, seeds)]
        _run(eng, hs)
        for h, r in zip(hs, greedy_ref):
            assert h.tokens == r
        eng2 = _paged(m)
        hs2 = [eng2.add_request(p, max_new_tokens=6, seed=s, **kw)
               for p, s in zip(prompts, seeds)]
        _run(eng2, hs2)
        for h, r in zip(hs2, sampled_ref):
            assert h.tokens == r

    def test_pallas_int8_identity(self, pallas_mode):
        m = _model()
        prompts, seeds = self._prompts(22), [6, 7, 8]
        set_flags({"FLAGS_paged_kernel": "off"})
        refs = self._baseline(m, prompts, seeds)
        set_flags({"FLAGS_paged_kernel": "pallas"})
        eng = _paged(m, kv_dtype="int8")
        hs = [eng.add_request(p, max_new_tokens=6, seed=s)
              for p, s in zip(prompts, seeds)]
        _run(eng, hs)
        for h, r in zip(hs, refs):
            assert h.tokens == r

    # PTQ identity is also gated by check_counters.py's direct
    # prefill_slot logit-drift check; full-suite only.
    @pytest.mark.slow
    def test_ptq_weights_token_identity(self):
        m = _model()
        prompts, seeds = self._prompts(23), [9, 10, 11]
        refs = self._baseline(m, prompts, seeds)
        eng = _paged(m, weight_dtype="int8")
        assert eng.stats()["weight_dtype"] == "int8"
        hs = [eng.add_request(p, max_new_tokens=6, seed=s)
              for p, s in zip(prompts, seeds)]
        _run(eng, hs)
        for h, r in zip(hs, refs):
            assert h.tokens == r

    def test_quant_cow_and_prefix_identity(self):
        # COW with scale-row cloning + prefix sharing on a quantized arena
        m = _model()
        rng = np.random.default_rng(24)
        p1 = rng.integers(0, 64, size=10).tolist()
        eng = _paged(m, kv_dtype="int8")
        h1 = eng.add_request(p1, max_new_tokens=6, seed=12)
        _run(eng, [h1])
        base = self._baseline(m, [p1], [12])[0]
        assert h1.tokens == base
        seq1 = p1 + h1.tokens
        p2 = seq1[:15] + rng.integers(0, 64, size=4).tolist()
        h2 = eng.add_request(p2, max_new_tokens=5, seed=13)
        _run(eng, [h2])
        assert eng.stats()["cow_copies"] >= 1
        assert h2.tokens == self._baseline(m, [p2], [13], max_new=5)[0]

    def test_quant_counters_and_bytes_saved(self):
        from paddle_tpu.profiler import counters
        m = _model()
        before = counters.snapshot()
        eng = _paged(m, kv_dtype="int8")
        h = eng.add_request(list(range(8)), max_new_tokens=4, seed=0)
        _run(eng, [h])
        d = counters.delta(before)
        assert d.get("serving.kv.quant.prefill_tokens", 0) > 0
        assert d.get("serving.kv.quant.decode_tokens", 0) > 0
        assert counters.get("serving.kv.quant.bytes_saved") > 0

    def test_kv_dtype_validation(self):
        from paddle_tpu.serving import LLMEngine
        m = _model()
        with pytest.raises(ValueError, match="kv_dtype"):
            _paged(m, kv_dtype="int4")
        with pytest.raises(ValueError, match="paged"):
            LLMEngine(m, max_slots=2, max_seq_len=32, min_bucket=4,
                      kv_dtype="int8")            # slot arena can't quantize
        with pytest.raises(ValueError, match="weight_dtype"):
            _paged(m, weight_dtype="fp4")
        from paddle_tpu.serving.kvcache import BlockPool
        with pytest.raises(ValueError, match="kv_dtype"):
            BlockPool(4, 4, kv_dtype="int4")
