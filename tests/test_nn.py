"""nn layers + functional tests (reference: test/legacy_test per-layer
tests)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


def np_t(x):
    return np.asarray(x.numpy())


class TestLinear:
    def test_forward_backward(self):
        lin = nn.Linear(4, 3)
        x = paddle.randn([2, 4])
        y = lin(x)
        assert y.shape == [2, 3]
        assert np.allclose(np_t(y), np_t(x) @ np_t(lin.weight)
                           + np_t(lin.bias), atol=1e-5)
        y.sum().backward()
        assert lin.weight.grad is not None
        assert lin.bias.grad.shape == [3]

    def test_no_bias(self):
        lin = nn.Linear(4, 3, bias_attr=False)
        assert lin.bias is None


class TestActivations:
    def test_values(self):
        x = paddle.to_tensor([-1.0, 0.0, 2.0])
        assert np.allclose(np_t(F.relu(x)), [0, 0, 2])
        assert np.allclose(np_t(F.sigmoid(x)),
                           1 / (1 + np.exp([1, 0, -2])), rtol=1e-5)
        assert np.allclose(np_t(F.softmax(x)).sum(), 1.0, rtol=1e-6)
        assert np.allclose(np_t(F.gelu(paddle.to_tensor([0.0]))), [0.0])
        assert np.allclose(np_t(F.silu(x)), np_t(x) / (1 + np.exp(-np_t(x))),
                           rtol=1e-5)

    def test_layers(self):
        x = paddle.randn([3, 4])
        for L in [nn.ReLU(), nn.GELU(), nn.Tanh(), nn.Sigmoid(),
                  nn.LeakyReLU(0.1), nn.Softmax(-1), nn.Silu()]:
            assert L(x).shape == [3, 4]


class TestConv:
    def test_conv2d_shape(self):
        conv = nn.Conv2D(3, 8, 3, stride=2, padding=1)
        x = paddle.randn([2, 3, 16, 16])
        y = conv(x)
        assert y.shape == [2, 8, 8, 8]
        y.sum().backward()
        assert conv.weight.grad.shape == [8, 3, 3, 3]

    def test_conv2d_numpy_parity(self):
        # 1x1 conv == matmul
        conv = nn.Conv2D(2, 3, 1, bias_attr=False)
        x = paddle.randn([1, 2, 4, 4])
        y = conv(x)
        w = np_t(conv.weight).reshape(3, 2)
        expected = np.einsum("oc,bchw->bohw", w, np_t(x))
        assert np.allclose(np_t(y), expected, atol=1e-5)

    def test_groups_depthwise(self):
        conv = nn.Conv2D(4, 4, 3, padding=1, groups=4)
        assert conv(paddle.randn([1, 4, 8, 8])).shape == [1, 4, 8, 8]

    def test_conv_transpose(self):
        convt = nn.Conv2DTranspose(3, 2, 2, stride=2)
        y = convt(paddle.randn([1, 3, 4, 4]))
        assert y.shape == [1, 2, 8, 8]

    def test_conv1d(self):
        c = nn.Conv1D(2, 4, 3, padding=1)
        assert c(paddle.randn([2, 2, 10])).shape == [2, 4, 10]


class TestNorm:
    def test_batchnorm_train_eval(self):
        bn = nn.BatchNorm2D(3)
        x = paddle.randn([4, 3, 8, 8])
        bn.train()
        y = bn(x)
        out = np_t(y)
        assert abs(out.mean()) < 1e-4
        assert abs(out.std() - 1.0) < 1e-2
        # running stats updated
        assert not np.allclose(np_t(bn._mean), 0.0)
        bn.eval()
        y2 = bn(x)
        assert y2.shape == [4, 3, 8, 8]

    def test_layernorm(self):
        ln = nn.LayerNorm(8)
        x = paddle.randn([2, 4, 8])
        y = np_t(ln(x))
        assert np.allclose(y.mean(-1), 0, atol=1e-5)
        assert np.allclose(y.std(-1), 1, atol=1e-1)

    def test_rmsnorm(self):
        rn = nn.RMSNorm(8)
        x = paddle.randn([2, 8])
        y = np_t(rn(x))
        expected = np_t(x) / np.sqrt((np_t(x) ** 2).mean(-1, keepdims=True)
                                     + 1e-6)
        assert np.allclose(y, expected, atol=1e-5)

    def test_groupnorm(self):
        gn = nn.GroupNorm(2, 4)
        assert gn(paddle.randn([2, 4, 5, 5])).shape == [2, 4, 5, 5]


class TestPooling:
    def test_maxpool_avgpool(self):
        x = paddle.to_tensor(np.arange(16, dtype=np.float32).reshape(
            1, 1, 4, 4))
        y = F.max_pool2d(x, 2)
        assert np.allclose(np_t(y).reshape(-1), [5, 7, 13, 15])
        y = F.avg_pool2d(x, 2)
        assert np.allclose(np_t(y).reshape(-1), [2.5, 4.5, 10.5, 12.5])

    def test_adaptive(self):
        x = paddle.randn([2, 3, 8, 8])
        assert F.adaptive_avg_pool2d(x, 1).shape == [2, 3, 1, 1]
        assert F.adaptive_avg_pool2d(x, (2, 4)).shape == [2, 3, 2, 4]


class TestLosses:
    def test_cross_entropy(self):
        logits = paddle.to_tensor([[10.0, 0.0, 0.0], [0.0, 10.0, 0.0]])
        labels = paddle.to_tensor([0, 1])
        loss = F.cross_entropy(logits, labels)
        assert float(loss.numpy()) < 0.01
        # soft label
        soft = paddle.to_tensor([[1.0, 0, 0], [0, 1.0, 0]])
        loss2 = F.cross_entropy(logits, soft, soft_label=True)
        assert float(loss2.numpy()) < 0.01

    def test_ignore_index(self):
        logits = paddle.randn([4, 5])
        labels = paddle.to_tensor([0, -100, 2, -100])
        loss = F.cross_entropy(logits, labels)
        manual = F.cross_entropy(logits[paddle.to_tensor([0, 2])],
                                 paddle.to_tensor([0, 2]))
        assert abs(float(loss.numpy()) - float(manual.numpy())) < 1e-5

    def test_mse_l1_bce(self):
        a = paddle.to_tensor([1.0, 2.0])
        b = paddle.to_tensor([1.5, 1.0])
        assert abs(float(F.mse_loss(a, b).numpy()) - 0.625) < 1e-6
        assert abs(float(F.l1_loss(a, b).numpy()) - 0.75) < 1e-6
        p = paddle.to_tensor([0.9, 0.1])
        y = paddle.to_tensor([1.0, 0.0])
        assert float(F.binary_cross_entropy(p, y).numpy()) < 0.2

    def test_kl_smooth(self):
        lp = F.log_softmax(paddle.randn([2, 5]), -1)
        t = F.softmax(paddle.randn([2, 5]), -1)
        assert np.isfinite(float(F.kl_div(lp, t).numpy()))
        assert np.isfinite(float(F.smooth_l1_loss(
            paddle.randn([3]), paddle.randn([3])).numpy()))


class TestEmbeddingDropout:
    def test_embedding(self):
        emb = nn.Embedding(10, 4)
        ids = paddle.to_tensor([[1, 2], [3, 4]])
        out = emb(ids)
        assert out.shape == [2, 2, 4]
        assert np.allclose(np_t(out)[0, 0], np_t(emb.weight)[1])
        out.sum().backward()
        assert emb.weight.grad is not None

    def test_padding_idx(self):
        emb = nn.Embedding(10, 4, padding_idx=0)
        out = emb(paddle.to_tensor([0, 1]))
        assert np.allclose(np_t(out)[0], 0.0)

    def test_dropout(self):
        x = paddle.ones([100, 100])
        d = nn.Dropout(0.5)
        d.train()
        y = np_t(d(x))
        frac = (y == 0).mean()
        assert 0.3 < frac < 0.7
        # upscale: kept values are doubled
        assert np.allclose(y[y != 0], 2.0)
        d.eval()
        assert np.allclose(np_t(d(x)), 1.0)


class TestAttention:
    def test_sdpa_matches_naive(self):
        q = paddle.randn([2, 8, 2, 4])
        k = paddle.randn([2, 8, 2, 4])
        v = paddle.randn([2, 8, 2, 4])
        out = F.scaled_dot_product_attention(q, k, v)
        qn, kn, vn = np_t(q), np_t(k), np_t(v)
        logits = np.einsum("bshd,bthd->bhst", qn, kn) / np.sqrt(4)
        p = np.exp(logits - logits.max(-1, keepdims=True))
        p = p / p.sum(-1, keepdims=True)
        expected = np.einsum("bhst,bthd->bshd", p, vn)
        assert np.allclose(np_t(out), expected, atol=1e-4)

    def test_causal(self):
        q = paddle.randn([1, 6, 1, 8])
        out = F.scaled_dot_product_attention(q, q, q, is_causal=True)
        # first position attends only to itself -> equals v[0]
        assert np.allclose(np_t(out)[0, 0, 0], np_t(q)[0, 0, 0], atol=1e-5)

    def test_multihead_layer(self):
        mha = nn.MultiHeadAttention(16, 4)
        x = paddle.randn([2, 5, 16])
        out = mha(x)
        assert out.shape == [2, 5, 16]
        out.sum().backward()
        assert mha.q_proj.weight.grad is not None


class TestTransformer:
    def test_encoder(self):
        layer = nn.TransformerEncoderLayer(16, 4, 32, dropout=0.0)
        enc = nn.TransformerEncoder(layer, 2)
        x = paddle.randn([2, 6, 16])
        out = enc(x)
        assert out.shape == [2, 6, 16]
        out.mean().backward()

    def test_full_transformer(self):
        tr = nn.Transformer(d_model=16, nhead=4, num_encoder_layers=1,
                            num_decoder_layers=1, dim_feedforward=32,
                            dropout=0.0)
        src = paddle.randn([2, 5, 16])
        tgt = paddle.randn([2, 3, 16])
        out = tr(src, tgt)
        assert out.shape == [2, 3, 16]


class TestRNN:
    def test_lstm(self):
        lstm = nn.LSTM(4, 8, num_layers=1)
        x = paddle.randn([2, 5, 4])
        out, (h, c) = lstm(x)
        assert out.shape == [2, 5, 8]
        assert h.shape == [1, 2, 8]
        out.sum().backward()

    def test_gru_bidirect(self):
        gru = nn.GRU(4, 8, direction="bidirect")
        out, h = gru(paddle.randn([2, 5, 4]))
        assert out.shape == [2, 5, 16]


class TestLayerBase:
    def test_state_dict_roundtrip(self):
        net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        sd = net.state_dict()
        assert len(sd) == 4
        net2 = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        net2.set_state_dict(sd)
        x = paddle.randn([1, 4])
        assert np.allclose(np_t(net(x)), np_t(net2(x)))

    def test_named_parameters(self):
        net = nn.Sequential(nn.Linear(2, 2))
        names = [n for n, _ in net.named_parameters()]
        assert names == ["0.weight", "0.bias"]

    def test_hooks(self):
        lin = nn.Linear(2, 2)
        calls = []
        h = lin.register_forward_post_hook(
            lambda l, i, o: calls.append(1))
        lin(paddle.randn([1, 2]))
        assert calls == [1]
        h.remove()
        lin(paddle.randn([1, 2]))
        assert calls == [1]

    def test_train_eval_propagates(self):
        net = nn.Sequential(nn.Dropout(0.5))
        net.eval()
        assert not net[0].training
        net.train()
        assert net[0].training

    def test_layerlist_parameterlist(self):
        ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
        assert len(ll) == 3
        assert len(list(ll.parameters())) == 6

    def test_clip_grad(self):
        lin = nn.Linear(4, 4)
        (lin(paddle.randn([8, 4])) * 100).sum().backward()
        nn.clip_grad_norm_(lin.parameters(), 1.0)
        total = sum(float((p.grad * p.grad).sum().numpy())
                    for p in lin.parameters())
        assert total <= 1.01


class TestSparseAttention:
    """CSR-masked attention (reference: test_sparse_attention_op.py);
    a causal CSR pattern must reproduce dense causal attention."""

    def test_causal_csr_matches_dense(self):
        import torch

        rng = np.random.RandomState(0)
        B, H, S, D = 1, 2, 6, 4
        q, k, v = (rng.rand(B, H, S, D).astype(np.float32)
                   for _ in range(3))
        off = np.zeros((B, H, S + 1), np.int64)
        for i in range(S):
            off[:, :, i + 1] = off[:, :, i] + (i + 1)
        cols = np.asarray([c for i in range(S) for c in range(i + 1)],
                          np.int64)
        col = np.broadcast_to(cols, (B, H, cols.size)).copy()
        out = F.sparse_attention(
            paddle.to_tensor(q), paddle.to_tensor(k), paddle.to_tensor(v),
            paddle.to_tensor(off), paddle.to_tensor(col)).numpy()
        ref = torch.nn.functional.scaled_dot_product_attention(
            *(torch.from_numpy(a) for a in (q, k, v)),
            is_causal=True).numpy()
        np.testing.assert_allclose(out, ref, atol=1e-5)

    def test_empty_row_outputs_zero(self):
        q = paddle.to_tensor(np.ones((1, 1, 2, 4), np.float32))
        off = paddle.to_tensor(np.array([[[0, 0, 1]]], np.int64))  # row 0 empty
        col = paddle.to_tensor(np.array([[[1]]], np.int64))
        out = F.sparse_attention(q, q, q, off, col).numpy()
        np.testing.assert_allclose(out[0, 0, 0], 0.0)
        np.testing.assert_allclose(out[0, 0, 1], 1.0, atol=1e-6)


class TestConvTransposeStringPadding:
    def test_same_doubles_with_stride2(self):
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.rand(1, 3, 8, 8).astype(np.float32))
        w = paddle.to_tensor(rng.rand(3, 4, 4, 4).astype(np.float32))
        y = F.conv2d_transpose(x, w, stride=2, padding="SAME")
        assert list(y.shape) == [1, 4, 16, 16]

    def test_valid_is_unpadded(self):
        x = paddle.to_tensor(np.zeros((1, 3, 8, 8), np.float32))
        w = paddle.to_tensor(np.zeros((3, 4, 4, 4), np.float32))
        y = F.conv2d_transpose(x, w, stride=2, padding="VALID")
        assert list(y.shape) == [1, 4, 18, 18]

    def test_same_rejected_when_kernel_smaller_than_stride(self):
        x = paddle.to_tensor(np.zeros((1, 3, 8, 8), np.float32))
        w = paddle.to_tensor(np.zeros((3, 4, 2, 2), np.float32))
        with pytest.raises(ValueError, match="SAME"):
            F.conv2d_transpose(x, w, stride=4, padding="SAME")


class TestConvTransposeOutputSize:
    def test_output_size_selects_output_padding(self):
        x = paddle.to_tensor(np.zeros((1, 3, 8, 8), np.float32))
        w = paddle.to_tensor(np.zeros((3, 4, 3, 3), np.float32))
        # base out = (8-1)*2 + 3 = 17; output_size 18 => opad 1
        y = F.conv2d_transpose(x, w, stride=2, padding=0,
                               output_size=[18, 18])
        assert list(y.shape) == [1, 4, 18, 18]

    def test_unreachable_output_size_rejected(self):
        x = paddle.to_tensor(np.zeros((1, 3, 8, 8), np.float32))
        w = paddle.to_tensor(np.zeros((3, 4, 3, 3), np.float32))
        with pytest.raises(ValueError, match="output_size"):
            F.conv2d_transpose(x, w, stride=2, padding=0,
                               output_size=[25, 25])


def test_sparse_attention_masks_rejected():
    q = paddle.to_tensor(np.ones((1, 1, 2, 4), np.float32))
    off = paddle.to_tensor(np.array([[[0, 1, 2]]], np.int64))
    col = paddle.to_tensor(np.array([[[0, 1]]], np.int64))
    with pytest.raises(NotImplementedError, match="CSR"):
        F.sparse_attention(q, q, q, off, col, key_padding_mask=q)
