"""Request-level distributed tracing (profiler.trace), the goodput
ledger (profiler.goodput), and the live ops endpoint (profiler.ops).

The load-bearing contracts:

* OFF is free: ``FLAGS_request_trace_sample=0`` mints no contexts and
  moves no ``trace.*`` counters (every record site gates on the context
  being None) — the machine-checked version lives in
  scripts/check_counters.py's trace phase.
* ON tells the truth: a served request's span tree names every hop
  (queue → prefill → decode.iter* → evict), the stage sums account the
  measured wall time, and ONE trace_id survives replica churn.
* Tail sampling keeps what matters: deadline-breached / errored /
  retried requests are retained even at a vanishing head sample rate.
* The goodput ledger accounts >=99% of trainer wall time into named
  buckets, clean or faulted.
* The ops endpoint serves all of it over stdlib HTTP.
"""

import json
import threading
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import flags
from paddle_tpu.profiler import counters
from paddle_tpu.profiler import trace as rtrace


@pytest.fixture(autouse=True)
def _trace_reset():
    """Every test leaves tracing OFF and the kept-ring empty."""
    yield
    flags.set_flags({"FLAGS_request_trace_sample": 0.0})
    rtrace.clear()


def _on(rate=1.0):
    flags.set_flags({"FLAGS_request_trace_sample": float(rate)})


@pytest.fixture(scope="module")
def model():
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=32,
                    use_flash_attention=False)
    paddle.seed(31)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


def _engine(m, **kw):
    from paddle_tpu.serving import LLMEngine
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_seq_len", 32)
    kw.setdefault("min_bucket", 4)
    return LLMEngine(m, **kw)


def _serve(eng, hs):
    while not all(h.is_finished for h in hs):
        eng.step()
    return hs


def _names(ctx):
    return [s[2] for s in ctx.spans]


class TestSampling:
    def test_off_by_default_mints_nothing(self, model):
        assert not rtrace.enabled()
        assert rtrace.new_trace(7) is None
        before = counters.snapshot()
        eng = _engine(model)
        h = eng.add_request([1, 2, 3], max_new_tokens=3)
        _serve(eng, [h])
        d = counters.delta(before)
        assert h.trace is None
        assert not any(k.startswith("trace.") and v for k, v in d.items())
        assert rtrace.kept_ids() == []

    def test_tail_keeps_deadline_breach_at_tiny_sample(self, model):
        """head_sampled is (effectively) never true at 1e-9, but a
        deadline-breached request is retained anyway — the tail is
        exactly the traffic worth debugging."""
        _on(1e-9)
        eng = _engine(model)
        h = eng.add_request([1, 2, 3, 4], max_new_tokens=16,
                            deadline_s=0.0)
        _serve(eng, [h])
        assert h.finish_reason == "deadline"
        assert h.trace is not None
        assert h.trace.head_sampled is False
        assert h.trace.keep_reason == "tail:deadline"
        assert h.trace.trace_id in rtrace.kept_ids()

    def test_finish_is_idempotent_and_blocks_late_spans(self):
        _on(1.0)
        ctx = rtrace.new_trace(5)
        ctx.add_span("queue", 0, 10)
        assert rtrace.finish(ctx, "length") is True
        n = len(ctx.spans)
        assert ctx.add_span("late", 0, 1) is None   # finished: dropped
        assert rtrace.finish(ctx, "length") is False  # second call: no-op
        assert len(ctx.spans) == n


class TestSpanTrees:
    def test_slot_engine_span_tree(self, model):
        _on(1.0)
        eng = _engine(model)
        h = _serve(eng, [eng.add_request([1, 2, 3, 4, 5],
                                         max_new_tokens=3)])[0]
        ctx = h.trace
        assert ctx is not None and ctx.finished
        names = _names(ctx)
        assert "queue" in names
        assert "prefill" in names
        # prefill emits token 1; decode iterations emit the rest
        assert names.count("decode.iter") == 2
        assert "evict" in names                     # terminal marker
        d = ctx.to_dict()
        assert d["status"] == "length"
        assert d["tree"]["name"] == f"request[rid={h.rid}]"
        assert len(d["tree"]["children"]) == len(ctx.spans)
        assert all(d["stage_ns"][s] > 0
                   for s in ("queue", "prefill", "decode"))

    def test_paged_engine_records_kv_and_chunk_spans(self, model):
        _on(1.0)
        eng = _engine(model, kv_layout="paged", block_size=4,
                      prefill_chunk=8)
        h = _serve(eng, [eng.add_request(list(range(1, 13)),
                                         max_new_tokens=3)])[0]
        names = _names(h.trace)
        assert "kv.reserve" in names
        assert names.count("prefill.chunk") == 2   # 12 tokens / chunk 8
        assert names.count("decode.iter") == 2

    def test_stage_sums_account_measured_wall(self, model):
        """queue + prefill + decode span time ~= arrival -> last emit."""
        _on(1.0)
        eng = _engine(model)
        h = _serve(eng, [eng.add_request([1, 2, 3, 4, 5, 6],
                                         max_new_tokens=4)])[0]
        measured = h.last_emit_ns - h.arrival_ns
        ratio = sum(h.trace.stage_ns().values()) / max(1, measured)
        assert 0.2 <= ratio <= 1.3, ratio

    def test_concurrent_add_span_is_safe(self):
        _on(1.0)
        ctx = rtrace.new_trace(9)
        n_threads, per = 8, 200

        def work(i):
            for j in range(per):
                ctx.add_span(f"w{i}", j, j + 1, k=j)

        ts = [threading.Thread(target=work, args=(i,))
              for i in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert len(ctx.spans) == n_threads * per
        sids = [s[0] for s in ctx.spans]
        assert len(set(sids)) == len(sids)          # unique span ids
        rtrace.finish(ctx, "length")
        assert len(ctx.to_dict()["spans"]) == n_threads * per


@pytest.mark.slow
class TestFleetTracing:
    def test_trace_id_survives_replica_respawn(self, model):
        """The respawned re-prefill lands in the SAME trace: one story
        per request, with redispatch + replica_died markers."""
        from paddle_tpu.resilience import faultinject
        from paddle_tpu.serving import ServingFleet
        _on(1.0)
        fleet = ServingFleet(model, replicas=2, max_slots=2,
                             max_seq_len=32, min_bucket=4, threaded=False,
                             warm_buckets=(4,))
        h = fleet.submit([1, 2, 3], max_new_tokens=4)
        tid = h.trace.trace_id
        with faultinject.fault_schedule(f"replica_crash@{h.rid}"):
            fleet.join([h])
        fleet.drain()
        assert h.finish_reason == "length"
        assert h.retries == 1
        ctx = h.trace
        assert ctx.trace_id == tid
        names = _names(ctx)
        assert "replica_died" in names
        assert "redispatch" in names
        assert names.count("prefill") == 2          # original + replay
        assert ctx.keep_reason == "tail:retried"
        assert rtrace.get_trace(tid)["rid"] == h.rid

    def test_slow_decode_stalls_are_spanned_and_counted(self, model):
        from paddle_tpu.resilience import faultinject
        from paddle_tpu.serving import ServingFleet
        _on(1.0)
        fleet = ServingFleet(model, replicas=1, max_slots=2,
                             max_seq_len=32, min_bucket=4, threaded=False,
                             warm_buckets=(4,))
        before = counters.snapshot()
        h = fleet.submit([1, 2, 3], max_new_tokens=6)
        with faultinject.fault_schedule(f"slow_decode@{h.rid}*3"):
            fleet.join([h])
        fleet.drain()
        assert h.finish_reason == "length"          # stalled, not killed
        stalls = [s for s in h.trace.spans if s[2] == "decode.stall"]
        assert len(stalls) == 3
        assert all((s[5] or {}).get("injected") for s in stalls)
        d = counters.delta(before)
        assert d.get("serving.fleet.slow_decode_stalls", 0) == 3


class TestGoodputLedger:
    def test_exclusive_buckets_and_accounting(self):
        import time
        from paddle_tpu.profiler.goodput import GoodputLedger
        led = GoodputLedger()
        led.start()
        with led.bucket("step"):
            time.sleep(0.02)
            with led.bucket("ckpt_sync"):   # child pauses the parent
                time.sleep(0.02)
            time.sleep(0.01)
        led.stop()
        r = led.report(publish=False)
        assert r["accounted"] >= 0.99
        # exclusive time: the nested ckpt_sync is NOT double-counted
        # under step (step ~30ms of the 50ms wall, never ~50ms)
        assert r["buckets_ns"]["ckpt_sync"] >= 15e6
        assert 25e6 <= r["buckets_ns"]["step"] <= 45e6
        assert r["wall_ns"] >= r["buckets_ns"]["step"]

    def test_trainer_wall_time_accounted_under_preempt(self):
        import tempfile
        import paddle_tpu.jit as pjit
        import paddle_tpu.nn as nn
        from paddle_tpu.io import DataLoader, TensorDataset
        from paddle_tpu.resilience import (CheckpointManager,
                                           FaultTolerantTrainer,
                                           faultinject)

        paddle.seed(7)
        net = nn.Sequential(nn.Linear(6, 12), nn.GELU(), nn.Linear(12, 3))
        opt = paddle.optimizer.AdamW(5e-2, parameters=net.parameters())
        step = pjit.CompiledTrainStep(
            net, lambda m, a, b: ((m(a) - b) ** 2).mean(), opt)
        rng = np.random.RandomState(3)
        ds = TensorDataset(
            [paddle.to_tensor(rng.randn(24, 6).astype("float32")),
             paddle.to_tensor(rng.randn(24, 3).astype("float32"))])
        with tempfile.TemporaryDirectory() as d:
            trainer = FaultTolerantTrainer(
                step, lambda e: DataLoader(ds, batch_size=4,
                                           shuffle=False),
                CheckpointManager(d, keep_last=2),
                epochs=1, max_steps=6, save_every=2)
            with faultinject.fault_schedule("preempt@3"):
                losses = trainer.run()
        assert len(losses) == 6
        r = trainer.goodput.report(publish=False)
        assert r["accounted"] >= 0.99, r
        assert 0.0 < r["goodput"] <= 1.0
        assert r["buckets_ns"]["compile"] > 0
        assert r["buckets_ns"]["step"] > 0
        assert r["buckets_ns"]["recovery"] > 0          # faulted run
        assert r["buckets_ns"]["restore_replay"] > 0
        # the split is exhaustive: buckets (idle-folded) sum to wall
        assert abs(sum(r["buckets_ns"].values())
                   - r["wall_ns"]) <= 0.01 * r["wall_ns"]


class TestOpsEndpoint:
    def _get(self, url):
        with urllib.request.urlopen(url, timeout=10) as resp:
            return resp.status, resp.read()

    def test_endpoints_serve_live_state(self, model):
        from paddle_tpu.profiler.goodput import GoodputLedger
        from paddle_tpu.profiler.ops import OpsServer
        _on(1.0)
        eng = _engine(model)
        h = _serve(eng, [eng.add_request([1, 2, 3], max_new_tokens=2)])[0]
        import time
        led = GoodputLedger()
        led.start()
        with led.bucket("step"):
            time.sleep(0.05)   # dwell so attribution dominates overhead
        led.stop()
        with OpsServer(engine=eng, ledger=led) as srv:
            code, body = self._get(srv.url("/healthz"))
            hz = json.loads(body)
            assert code == 200 and hz["status"] == "ok"
            assert hz["traces_kept"] >= 1

            code, body = self._get(srv.url("/metrics"))
            assert code == 200 and len(body) > 0

            code, body = self._get(srv.url("/traces"))
            tr = json.loads(body)
            assert code == 200 and h.trace.trace_id in tr["kept"]
            assert tr["breakdown"]["requests"] >= 1

            code, body = self._get(
                srv.url(f"/traces/{h.trace.trace_id}"))
            t = json.loads(body)
            assert code == 200 and t["rid"] == h.rid
            assert any(s["name"] == "prefill" for s in t["spans"])

            code, body = self._get(srv.url("/goodput"))
            g = json.loads(body)
            assert code == 200 and g["accounted"] >= 0.99

            code, body = self._get(srv.url("/flight"))
            assert code == 200 and "events" in json.loads(body)

            with pytest.raises(urllib.error.HTTPError) as ei:
                self._get(srv.url("/traces/nope"))
            assert ei.value.code == 404

    def test_goodput_404_without_ledger(self, model):
        from paddle_tpu.profiler.ops import OpsServer
        with OpsServer(engine=_engine(model)) as srv:
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._get(srv.url("/goodput"))
            assert ei.value.code == 404


class TestExport:
    def test_jsonl_and_chrome_export(self, tmp_path, model):
        _on(1.0)
        eng = _engine(model)
        _serve(eng, [eng.add_request([1, 2, 3, 4], max_new_tokens=2)])
        path = tmp_path / "traces.jsonl"
        rtrace.export_jsonl(str(path))
        recs = [json.loads(line)
                for line in path.read_text().splitlines()]
        assert len(recs) >= 1
        assert any(r["status"] == "length" for r in recs)
        ev = rtrace.to_chrome_trace()["traceEvents"]
        assert any(e.get("ph") == "X" and e.get("name") == "prefill"
                   for e in ev)
