"""1F1B x tensor-parallel composition (the reference's flagship TP x PP
recipe: fleet/meta_parallel/pipeline_parallel.py:459 composing with
mp_layers ColumnParallel/RowParallel + ParallelCrossEntropy).

The stage bodies here are MANUAL TP (distributed/mp_ops.py) under
shard_map{'pp','mp'}; parity target is plain eager training of the same
weights."""

import numpy as np
import pytest

import paddle_tpu as paddle


def np_t(x):
    return np.asarray(x.numpy())


@pytest.fixture()
def mesh_pp2_mp2():
    # function-scoped + idempotent: re-inits only when another fixture
    # (e.g. mesh_pp2_mp4) changed the global mesh in between
    import jax
    if jax.device_count() < 8:
        pytest.skip("needs 8 devices")
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.env import hybrid_degrees
    deg = hybrid_degrees()
    if (deg.get("pp"), deg.get("mp"), deg.get("dp")) != (2, 2, 2):
        fleet._reset()
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2,
                                   "pp_degree": 2}
        fleet.init(is_collective=True, strategy=strategy)
    yield fleet


@pytest.fixture()
def mesh_pp2_mp4():
    import jax
    if jax.device_count() < 8:
        pytest.skip("needs 8 devices")
    from paddle_tpu.distributed import fleet
    fleet._reset()
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 1, "mp_degree": 4, "pp_degree": 2}
    hcg = fleet.init(is_collective=True, strategy=strategy)
    yield hcg
    fleet._reset()


class TestMpOps:
    def test_vocab_parallel_ce_matches_dense(self, mesh_pp2_mp2):
        """vocab_parallel_ce_sum over sharded logits == dense CE sum, in
        value and in gradient (reference: ParallelCrossEntropy,
        c_softmax_with_cross_entropy_op)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P
        from paddle_tpu.distributed import get_mesh
        from paddle_tpu.distributed.mp_ops import vocab_parallel_ce_sum

        rng = np.random.default_rng(0)
        B, S, V = 4, 8, 32
        logits = jnp.asarray(rng.normal(size=(B, S, V)) * 3, jnp.float32)
        labels = jnp.asarray(rng.integers(0, V, size=(B, S)), jnp.int32)

        def dense(lg):
            lse = jax.nn.logsumexp(lg, -1)
            picked = jnp.take_along_axis(lg, labels[..., None], -1)[..., 0]
            return jnp.sum(lse - picked)

        ref_loss, ref_g = jax.value_and_grad(dense)(logits)

        mesh = get_mesh()

        # grad taken INSIDE the shard_map region (the same structure the
        # 1F1B tick uses: jax.vjp within the manual body)
        def local(l):
            return jax.value_and_grad(
                lambda ll: vocab_parallel_ce_sum(ll, labels, "mp"))(l)

        loss, g = jax.jit(jax.shard_map(
            local, mesh=mesh, in_specs=P(None, None, "mp"),
            out_specs=(P(), P(None, None, "mp")),
            axis_names={"mp"}, check_vma=False))(logits)
        assert np.allclose(float(loss), float(ref_loss), rtol=1e-5)
        assert np.allclose(np.asarray(g), np.asarray(ref_g), atol=1e-5)


class TestPipeline1F1BWithTP:
    def test_gpt_1f1b_tp_matches_eager(self, mesh_pp2_mp2):
        """Pipeline1F1BTrainStep on a pp2 x mp2 x dp2 mesh: loss series ==
        eager tape training with identical weights."""
        from paddle_tpu.distributed.engine import Pipeline1F1BTrainStep
        from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                       GPTPretrainingCriterion)

        cfg = GPTConfig(vocab_size=32, hidden_size=16, num_layers=2,
                        num_heads=2, max_seq_len=8,
                        use_flash_attention=False, dropout=0.0)
        paddle.seed(11)
        model = GPTForCausalLM(cfg)
        ref = GPTForCausalLM(cfg)
        ref.set_state_dict({k: paddle.to_tensor(np_t(v).copy())
                            for k, v in model.state_dict().items()})
        ids = paddle.randint(0, 32, [4, 8])
        lab = paddle.randint(0, 32, [4, 8])

        opt = paddle.optimizer.SGD(0.1, parameters=model.parameters())
        step = Pipeline1F1BTrainStep(model, opt, num_microbatches=4)
        losses = [float(step(ids, lab).numpy()) for _ in range(3)]

        crit = GPTPretrainingCriterion()
        ropt = paddle.optimizer.SGD(0.1, parameters=ref.parameters())
        ref_losses = []
        for _ in range(3):
            loss = crit(ref(ids), lab)
            loss.backward()
            ropt.step()
            ropt.clear_grad()
            ref_losses.append(float(loss.numpy()))

        assert np.allclose(losses, ref_losses, rtol=2e-3), (
            losses, ref_losses)
        assert losses[-1] < losses[0]

    @pytest.mark.parametrize("tie", [True, False])
    def test_gpt_1f1b_mp4_matches_eager(self, mesh_pp2_mp4, tie):
        """mp=4 (the north-star TP degree) x pp=2, tied AND untied
        embeddings: the shard-major qkv permutation and the vocab-parallel
        head must hold at mp>2 (round-4 verdict weak #8)."""
        from paddle_tpu.distributed.engine import Pipeline1F1BTrainStep
        from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                       GPTPretrainingCriterion)

        cfg = GPTConfig(vocab_size=32, hidden_size=16, num_layers=2,
                        num_heads=4, max_seq_len=8,
                        use_flash_attention=False, dropout=0.0,
                        tie_word_embeddings=tie)
        paddle.seed(13)
        model = GPTForCausalLM(cfg)
        ref = GPTForCausalLM(cfg)
        ref.set_state_dict({k: paddle.to_tensor(np_t(v).copy())
                            for k, v in model.state_dict().items()})
        ids = paddle.randint(0, 32, [4, 8])
        lab = paddle.randint(0, 32, [4, 8])

        opt = paddle.optimizer.SGD(0.1, parameters=model.parameters())
        step = Pipeline1F1BTrainStep(model, opt, num_microbatches=4)
        losses = [float(step(ids, lab).numpy()) for _ in range(3)]

        crit = GPTPretrainingCriterion()
        ropt = paddle.optimizer.SGD(0.1, parameters=ref.parameters())
        ref_losses = []
        for _ in range(3):
            loss = crit(ref(ids), lab)
            loss.backward()
            ropt.step()
            ropt.clear_grad()
            ref_losses.append(float(loss.numpy()))

        assert np.allclose(losses, ref_losses, rtol=2e-3), (
            losses, ref_losses)
        assert losses[-1] < losses[0]

    def test_gpt_1f1b_tp_dropout_trains(self, mesh_pp2_mp2):
        """dropout>0 under 1F1B x TP: per-(microbatch, layer) fold_in keys
        replay deterministically (round-4 verdict weak #4 — this path used
        to raise NotImplementedError).  Two identical runs produce the
        identical loss series; training decreases the loss."""
        from paddle_tpu.distributed.engine import Pipeline1F1BTrainStep
        from paddle_tpu.models import GPTConfig, GPTForCausalLM

        def run():
            cfg = GPTConfig(vocab_size=32, hidden_size=16, num_layers=2,
                            num_heads=2, max_seq_len=8,
                            use_flash_attention=False, dropout=0.2)
            paddle.seed(17)
            model = GPTForCausalLM(cfg)
            model.train()
            ids = paddle.randint(0, 32, [4, 8])
            lab = paddle.randint(0, 32, [4, 8])
            opt = paddle.optimizer.SGD(0.1, parameters=model.parameters())
            step = Pipeline1F1BTrainStep(model, opt, num_microbatches=4)
            return [float(step(ids, lab).numpy()) for _ in range(4)]

        l1 = run()
        l2 = run()
        assert all(np.isfinite(l1)), l1
        assert np.allclose(l1, l2, rtol=1e-5), (l1, l2)  # RNG replay
        assert l1[-1] < l1[0], l1
