"""Autograd engine tests (reference pattern: op_test.py check_grad —
analytic vs numeric)."""

import numpy as np
import pytest

import paddle_tpu as paddle


def np_t(x):
    return np.asarray(x.numpy())


class TestBackward:
    def test_simple(self):
        x = paddle.to_tensor([2.0, 3.0], stop_gradient=False)
        y = (x * x).sum()
        y.backward()
        assert np.allclose(np_t(x.grad), [4, 6])

    def test_chain(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        y = paddle.exp(x * 2)
        z = paddle.log(y)  # z = 2x -> dz/dx = 2
        z.backward()
        assert np.allclose(np_t(x.grad), [2.0], atol=1e-5)

    def test_accumulation(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        (x * 2).backward()
        (x * 3).backward()
        assert np.allclose(np_t(x.grad), [5.0])

    def test_branching(self):
        x = paddle.to_tensor([2.0], stop_gradient=False)
        a = x * 3
        b = a + a * a  # d/da = 1 + 2a = 13; da/dx = 3 -> 39
        b.backward()
        assert np.allclose(np_t(x.grad), [39.0])

    def test_matmul_grad(self):
        a = paddle.to_tensor(np.random.randn(3, 4).astype(np.float32),
                             stop_gradient=False)
        b = paddle.to_tensor(np.random.randn(4, 2).astype(np.float32),
                             stop_gradient=False)
        paddle.matmul(a, b).sum().backward()
        assert np.allclose(np_t(a.grad), np_t(b).sum(1)[None, :].repeat(3, 0),
                           atol=1e-5)

    def test_retain_graph(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        y = x * x
        y.backward(retain_graph=True)
        y.backward()
        assert np.allclose(np_t(x.grad), [4.0])

    def test_freed_graph_raises(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        y = x * x
        y.backward()
        with pytest.raises(RuntimeError):
            y.backward()

    def test_stop_gradient(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        y = paddle.to_tensor([2.0])  # stop_gradient True
        z = x * y
        z.backward()
        assert np.allclose(np_t(x.grad), [2.0])
        assert y.grad is None

    def test_detach(self):
        x = paddle.to_tensor([3.0], stop_gradient=False)
        y = (x * x).detach()
        z = y * x
        z.backward()
        assert np.allclose(np_t(x.grad), [9.0])

    def test_non_scalar_backward_with_grad(self):
        x = paddle.to_tensor([1.0, 2.0], stop_gradient=False)
        y = x * 3
        y.backward(paddle.to_tensor([1.0, 10.0]))
        assert np.allclose(np_t(x.grad), [3.0, 30.0])

    def test_hook(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        seen = []
        x.register_hook(lambda g: seen.append(float(g.numpy())))
        (x * 5).backward()
        assert seen == [5.0]

    def test_numeric_grad_check(self):
        # analytic vs numeric for a composite fn (OpTest check_grad pattern)
        def f(a):
            return float((paddle.tanh(a) * paddle.exp(-a)).sum().numpy())

        x_np = np.array([0.3, -0.7, 1.2], np.float32)
        x = paddle.to_tensor(x_np, stop_gradient=False)
        (paddle.tanh(x) * paddle.exp(-x)).sum().backward()
        eps = 1e-3
        for i in range(3):
            xp = x_np.copy()
            xp[i] += eps
            xm = x_np.copy()
            xm[i] -= eps
            num = (f(paddle.to_tensor(xp)) - f(paddle.to_tensor(xm))) / (2 * eps)
            assert abs(float(np_t(x.grad)[i]) - num) < 1e-2


class TestGradAPI:
    def test_paddle_grad(self):
        x = paddle.to_tensor([2.0], stop_gradient=False)
        y = x ** 3
        (g,) = paddle.grad(y, x)
        assert np.allclose(np_t(g), [12.0])
        assert x.grad is None  # grad() must not touch .grad

    def test_no_grad(self):
        x = paddle.to_tensor([1.0], stop_gradient=False)
        with paddle.no_grad():
            y = x * 2
        assert y._node is None

    def test_pylayer(self):
        class Double(paddle.autograd.PyLayer):
            @staticmethod
            def forward(ctx, x):
                ctx.save_for_backward(x)
                return x * 2

            @staticmethod
            def backward(ctx, grad):
                return grad * 2

        x = paddle.to_tensor([3.0], stop_gradient=False)
        y = Double.apply(x)
        assert np.allclose(np_t(y), [6.0])
        y.backward()
        assert np.allclose(np_t(x.grad), [2.0])

    def test_functional_vjp_jvp(self):
        def f(x):
            return x * x

        out, g = paddle.autograd.vjp(f, paddle.to_tensor([3.0]))
        assert np.allclose(np_t(out), [9.0])
        out, t = paddle.autograd.jvp(f, paddle.to_tensor([3.0]))
        assert np.allclose(np_t(t), [6.0])

    def test_jacobian_hessian(self):
        x = paddle.to_tensor([1.0, 2.0])
        jac = paddle.autograd.jacobian(lambda v: v * v, x)
        assert np.allclose(jac.numpy(), np.diag([2.0, 4.0]))
