"""Speculative decoding (paddle_tpu.serving.speculative / .sampling).

The load-bearing contracts: (1) greedy speculative output is
TOKEN-IDENTICAL to the non-speculative paged engine (and therefore to
sequential ``GPT.generate``) for ANY draft model; (2) seeded sampling is
DISTRIBUTION-preserving — the emitted-token distribution matches the
non-speculative engine's (modified rejection sampling, Leviathan et al.
ICML 2023), proven by a chi-squared test over a small vocab; (3) the
draft namespace shares the target's ``BlockPool`` with exact refcount
accounting — rejection rollback releases blocks by table truncation and
a finished/cancelled/expired request leaks nothing; (4) the fleet path
threads ``draft_model=`` through replicas and loses zero requests when a
replica dies mid-draft."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.profiler import counters
from paddle_tpu.resilience import faultinject
from paddle_tpu.serving.kvcache import blocks_for_tokens

_MODELS = None


def _models():
    """(target, draft) pair on a shared 64-token vocab.  Different seeds
    and depths so drafts genuinely disagree with the target (rejections
    and rollbacks happen) — the contracts must hold for ANY draft."""
    global _MODELS
    if _MODELS is None:
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=4, max_seq_len=32,
                        use_flash_attention=False)
        paddle.seed(31)
        target = GPTForCausalLM(cfg)
        target.eval()
        dcfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=1,
                         num_heads=4, max_seq_len=32,
                         use_flash_attention=False)
        paddle.seed(7)
        draft = GPTForCausalLM(dcfg)
        draft.eval()
        _MODELS = (target, draft)
    return _MODELS


def _nb(max_slots, max_seq_len=32, block_size=4):
    """Pool size covering BOTH namespaces at every slot's worst case."""
    return 2 * max_slots * blocks_for_tokens(max_seq_len, block_size) + 1


def _spec(target, draft, **kw):
    from paddle_tpu.serving import LLMEngine
    kw.setdefault("max_slots", 3)
    kw.setdefault("max_seq_len", 32)
    kw.setdefault("min_bucket", 4)
    kw.setdefault("block_size", 4)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("spec_k", 3)
    kw.setdefault("n_blocks", _nb(kw["max_slots"], kw["max_seq_len"],
                                  kw["block_size"]))
    return LLMEngine(target, draft_model=draft, kv_layout="paged", **kw)


def _paged(target, **kw):
    from paddle_tpu.serving import LLMEngine
    kw.setdefault("max_slots", 3)
    kw.setdefault("max_seq_len", 32)
    kw.setdefault("min_bucket", 4)
    kw.setdefault("block_size", 4)
    kw.setdefault("prefill_chunk", 8)
    return LLMEngine(target, kv_layout="paged", **kw)


def _ref_generate(m, prompt, max_new, **kw):
    out = np.asarray(m.generate(paddle.to_tensor(np.asarray([prompt])),
                                max_new_tokens=max_new, **kw).numpy())[0]
    return out[len(prompt):].tolist()


def _run(eng, handles, limit=400):
    n = 0
    while not all(h.is_finished for h in handles):
        eng.step()
        n += 1
        assert n < limit, "engine did not converge"
    return n


class TestResidualSample:
    """Satellite unit tests for serving.sampling.residual_sample."""

    def _draw(self, p, q, n=4000, seed=0):
        import jax
        from paddle_tpu.serving.sampling import residual_sample
        keys = jax.random.split(jax.random.key(seed), n)
        toks = jax.vmap(lambda k: residual_sample(p, q, k))(keys)
        return np.asarray(toks)

    def test_matches_normalized_residual(self):
        import jax.numpy as jnp
        p = jnp.asarray([0.5, 0.3, 0.15, 0.05])
        q = jnp.asarray([0.1, 0.6, 0.25, 0.05])
        res = np.maximum(np.asarray(p) - np.asarray(q), 0.0)
        want = res / res.sum()
        toks = self._draw(p, q)
        freq = np.bincount(toks, minlength=4) / len(toks)
        # 4000 draws: binomial std <= 0.008 per bin — 0.03 is ~4 sigma
        assert np.abs(freq - want).max() < 0.03, (freq, want)

    def test_zero_residual_support_never_sampled(self):
        import jax.numpy as jnp
        p = jnp.asarray([0.5, 0.3, 0.15, 0.05])
        q = jnp.asarray([0.1, 0.6, 0.25, 0.05])
        toks = self._draw(p, q)
        # q >= p at indices 1, 2, 3: the residual there is exactly zero
        assert set(np.unique(toks)) == {0}

    def test_degenerate_equal_distributions_fall_back_to_p(self):
        import jax.numpy as jnp
        p = jnp.asarray([0.7, 0.2, 0.1, 0.0])
        toks = self._draw(p, p)          # residual mass exactly 0
        freq = np.bincount(toks, minlength=4) / len(toks)
        assert np.abs(freq - np.asarray(p)).max() < 0.03, freq
        assert 3 not in np.unique(toks)  # p(3)=0 stays unsampleable

    def test_batched_rows(self):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.serving.sampling import residual_sample
        p = jnp.asarray([[0.9, 0.1, 0.0], [0.0, 0.2, 0.8]])
        q = jnp.asarray([[0.1, 0.9, 0.0], [0.0, 0.8, 0.2]])
        keys = jax.random.split(jax.random.key(1), 2)
        toks = np.asarray(jax.vmap(residual_sample)(p, q, keys))
        assert toks[0] == 0 and toks[1] == 2   # only positive-residual bins


class TestGreedyIdentity:
    def test_token_identical_to_paged_engine_and_generate(self):
        target, draft = _models()
        rng = np.random.default_rng(0)
        prompts = [rng.integers(0, 64, size=n).tolist() for n in (5, 3, 9)]
        base = _paged(target)
        bh = [base.add_request(p, max_new_tokens=10) for p in prompts]
        _run(base, bh)
        spec = _spec(target, draft)
        sh = [spec.add_request(p, max_new_tokens=10) for p in prompts]
        _run(spec, sh)
        for b, s, p in zip(bh, sh, prompts):
            assert s.tokens == b.tokens, (s.tokens, b.tokens)
            assert s.tokens == _ref_generate(target, p, 10)
            assert s.finish_reason == b.finish_reason

    def test_identity_for_every_spec_k(self):
        """The acceptance logic is K-invariant: any draft depth emits the
        target's own greedy chain."""
        target, draft = _models()
        prompt = [3, 1, 4, 1, 5]
        ref = _ref_generate(target, prompt, 8)
        for k in (1, 2, 4):
            spec = _spec(target, draft, spec_k=k, max_slots=2)
            h = spec.add_request(prompt, max_new_tokens=8)
            _run(spec, [h])
            assert h.tokens == ref, (k, h.tokens, ref)

    def test_eos_and_length_finish_reasons(self):
        target, draft = _models()
        prompt = [2, 7, 2]
        ref = _ref_generate(target, prompt, 12)
        eos = ref[3]
        # eos mid-draft-block: the engine must stop emitting at the eos
        # token even when the verify round accepted tokens past it — same
        # truncation point as the non-speculative engine
        base = _paged(target, max_slots=2)
        b_eos = base.add_request(prompt, max_new_tokens=12,
                                 eos_token_id=eos)
        _run(base, [b_eos])
        spec = _spec(target, draft, max_slots=2)
        h_eos = spec.add_request(prompt, max_new_tokens=12, eos_token_id=eos)
        h_len = spec.add_request(prompt, max_new_tokens=12)
        _run(spec, [h_eos, h_len])
        assert h_len.tokens == ref and h_len.finish_reason == "length"
        assert h_eos.tokens == b_eos.tokens
        assert h_eos.finish_reason == b_eos.finish_reason == "eos"
        assert len(h_eos.tokens) < 12 and h_eos.tokens[-1] == eos


class TestDistributionPreservation:
    def test_chi_squared_small_vocab(self):
        """Modified rejection sampling leaves the output distribution
        equal to the target's own: the emitted-token histogram over many
        seeded requests must be chi-squared-compatible with the
        non-speculative paged engine's over the same seeds.  Fully
        deterministic (fixed seeds on both sides)."""
        target, draft = _models()
        prompt = [5, 9, 2, 6]
        kw = dict(max_new_tokens=4, do_sample=True, temperature=1.1,
                  top_k=8)
        n = 120

        def harvest(eng):
            counts = np.zeros(64, np.int64)
            pending = list(range(n))
            live = []
            while pending or live:
                while pending and len(live) < 8:
                    live.append(eng.add_request(
                        prompt, seed=1000 + pending.pop(0), **kw))
                eng.step()
                done = [h for h in live if h.is_finished]
                live = [h for h in live if not h.is_finished]
                for h in done:
                    for t in h.tokens:
                        counts[t] += 1
            return counts

        o1 = harvest(_paged(target, max_slots=4))
        o2 = harvest(_spec(target, draft, max_slots=4, spec_k=2))
        assert o1.sum() == o2.sum() == n * 4
        both = o1 + o2
        live_bins = both > 0
        # two-sample chi-squared: sum (o1-o2)^2/(o1+o2) ~ chi2(df)
        stat = float((((o1 - o2) ** 2)[live_bins]
                      / both[live_bins]).sum())
        df = int(live_bins.sum()) - 1
        # p=0.001 critical value for df<=63 is < df + 3.1*sqrt(2*df) + 12
        crit = df + 3.1 * np.sqrt(2 * df) + 12
        assert stat < crit, (stat, crit, df)

    def test_truncated_round_final_token_samples_from_target(self):
        """Budget exhaustion is NOT rejection: a row whose round is
        truncated below K+1 considered proposals (nv=1 here — the final
        token of every sampled request, and draft-starved rows) must draw
        its token from the target distribution ``p``, not from the
        residual ``norm(max(0, p - q))``.  Regression: the old acceptance
        folded ``j < nv-1`` into the accept bit, which read as a
        rejection and made every token where ``q >= p`` unsampleable at
        truncated positions."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.serving.speculative import _acceptance

        B, K1, V = 4096, 4, 4
        p0 = np.asarray([0.5, 0.3, 0.15, 0.05])
        q0 = np.asarray([0.9, 0.05, 0.03, 0.02])   # q > p at token 0
        logits = jnp.broadcast_to(jnp.log(jnp.asarray(p0, jnp.float32)),
                                  (B, K1, V))
        q = jnp.broadcast_to(jnp.asarray(q0, jnp.float32),
                             (B, K1 - 1, V))
        toks = jnp.zeros((B, K1), jnp.int32)
        nv = jnp.ones(B, jnp.int32)               # zero considered drafts
        keys_data = jax.random.key_data(
            jax.random.split(jax.random.key(11), B))
        emit, n_emit, _ = _acceptance(
            logits, toks, q, nv, keys_data,
            jnp.ones(B, bool), jnp.ones(B, jnp.float32),
            jnp.zeros(B, jnp.int32), jnp.ones(B, jnp.float32))
        assert np.all(np.asarray(n_emit) == 1)
        freq = np.bincount(np.asarray(emit)[:, 0], minlength=V) / B
        # 4096 draws: binomial std <= 0.008 per bin — 0.04 is ~5 sigma.
        # Under the residual bug freq[0] would be ~0 (residual mass at
        # token 0 is exactly zero), not ~0.5.
        assert np.abs(freq - p0).max() < 0.04, (freq, p0)

    def test_sampled_run_completes_and_counts_balance(self):
        target, draft = _models()
        rng = np.random.default_rng(3)
        prompts = [rng.integers(0, 64, size=n).tolist() for n in (4, 7)]
        spec = _spec(target, draft)
        before = counters.snapshot()
        hs = [spec.add_request(p, max_new_tokens=8, seed=50 + i,
                               do_sample=True, temperature=0.8, top_k=8,
                               top_p=0.9)
              for i, p in enumerate(prompts)]
        _run(spec, hs)
        d = counters.delta(before)
        assert all(len(h.tokens) == 8 for h in hs)
        assert all(0 <= t < 64 for h in hs for t in h.tokens)
        assert (d.get("serving.spec.accepted", 0)
                + d.get("serving.spec.rejected", 0)
                == d.get("serving.spec.drafted", 0) > 0)


class TestKVRollbackAccounting:
    def test_no_block_leak_after_rejections(self):
        """Rejection rollback truncates draft block tables and releases
        refcounts; with the prefix cache off a drained engine must own
        ZERO pool blocks — target and draft namespaces both."""
        target, draft = _models()
        spec = _spec(target, draft, prefix_cache=False)
        rng = np.random.default_rng(4)
        before = counters.snapshot()
        for _ in range(2):   # two waves reuse the same freed blocks
            hs = [spec.add_request(rng.integers(0, 64, size=n).tolist(),
                                   max_new_tokens=10) for n in (5, 9, 3)]
            _run(spec, hs)
        d = counters.delta(before)
        assert spec.pool.used_blocks == 0
        assert spec.pool.free_blocks == spec.pool.capacity
        # the mismatched draft really did get rolled back along the way
        assert d.get("serving.spec.rejected", 0) > 0
        assert d.get("serving.spec.rollback_blocks", 0) >= 0

    def test_draft_blocks_not_donated_to_prefix_cache(self):
        """With the prefix cache ON, finished TARGET blocks may stay
        resident in the radix tree but draft blocks must all be freed:
        the draft namespace is per-request scratch, never shared."""
        target, draft = _models()
        spec = _spec(target, draft, max_slots=2)
        h = spec.add_request([1, 2, 3, 4, 5, 6], max_new_tokens=8)
        _run(spec, [h])
        # every surviving reference is target-side: the prefix tree can
        # hold at most the target blocks of the one finished sequence
        max_target = blocks_for_tokens(6 + 8, spec.pool.block_size)
        assert spec.pool.used_blocks <= max_target
        assert all(t is None for t in spec._dslot_blocks)
        assert not spec._dbt.any()

    def test_missing_draft_table_degrades_to_plain_decode(self):
        """A running row whose draft table is gone must be downgraded to
        ``nv=1`` (``serving.spec.draft_starved``) instead of verifying
        proposals drafted against the trash block — the round degrades to
        plain decode and the greedy chain stays token-identical."""
        target, draft = _models()
        spec = _spec(target, draft, max_slots=2, prefix_cache=False)
        prompt = [1, 2, 3, 4, 5]
        ref = _ref_generate(target, prompt, 10)
        h = spec.add_request(prompt, max_new_tokens=10)
        while not any(r is not None and r.state == "running"
                      for r in spec._slots):
            spec.step()
        s = next(i for i, r in enumerate(spec._slots)
                 if r is not None and r.state == "running")
        with spec._cond:
            dbl = spec._dslot_blocks[s]
            spec._dslot_blocks[s] = None
            spec._dbt[s] = 0
            for b in dbl:
                spec.pool.release(b)
        before = counters.snapshot()
        _run(spec, [h])
        d = counters.delta(before)
        assert d.get("serving.spec.draft_starved", 0) > 0
        assert h.tokens == ref and h.finish_reason == "length"
        assert spec.pool.used_blocks == 0

    def test_pool_exhaustion_defers_not_crashes(self):
        """A pool too small for two doubled-namespace residents admits
        one request at a time — backpressure, not a crash."""
        target, draft = _models()
        spec = _spec(target, draft, max_slots=2, prefix_cache=False,
                     n_blocks=2 * blocks_for_tokens(20, 4) + 3)
        hs = [spec.add_request([7] * 5, max_new_tokens=12),
              spec.add_request([9] * 5, max_new_tokens=12)]
        _run(spec, hs)
        assert all(h.finish_reason == "length" for h in hs)
        assert all(len(h.tokens) == 12 for h in hs)
        assert spec.pool.used_blocks == 0


class TestCancellationAndDeadline:
    def test_mid_draft_cancellation_releases_both_namespaces(self):
        target, draft = _models()
        spec = _spec(target, draft, max_slots=2, prefix_cache=False)
        h_live = spec.add_request([1, 2, 3], max_new_tokens=10)
        h_dead = spec.add_request([4, 5, 6, 7, 8], max_new_tokens=20)
        for _ in range(3):   # past prefill, into the draft/verify rounds
            spec.step()
        h_dead.cancel()
        _run(spec, [h_live, h_dead])
        assert h_dead.finish_reason == "cancelled"
        assert len(h_dead.tokens) < 20
        assert h_live.finish_reason == "length"
        assert h_live.tokens == _ref_generate(target, [1, 2, 3], 10)
        assert spec.pool.used_blocks == 0

    def test_deadline_mid_decode(self):
        import time
        target, draft = _models()
        spec = _spec(target, draft, max_slots=2, prefix_cache=False)
        h = spec.add_request([3, 1, 4], max_new_tokens=25, deadline_s=0.01)
        spec.step()          # admit + begin prefill
        time.sleep(0.05)     # budget lapses mid-flight
        _run(spec, [h])
        assert h.finish_reason == "deadline"
        assert spec.pool.used_blocks == 0


class TestAcceptanceCounters:
    def test_round_economics_and_stats(self):
        target, draft = _models()
        spec = _spec(target, draft, spec_k=3, max_slots=2)
        before = counters.snapshot()
        hs = [spec.add_request([2, 4, 6], max_new_tokens=9),
              spec.add_request([1, 3, 5, 7], max_new_tokens=9)]
        _run(spec, hs)
        d = counters.delta(before)
        drafted = d.get("serving.spec.drafted", 0)
        assert drafted > 0
        assert (d.get("serving.spec.accepted", 0)
                + d.get("serving.spec.rejected", 0)) == drafted
        # K+1 draft launches + ONE verify launch per scheduler round
        assert d.get("serving.spec.draft_steps", 0) == \
            4 * d.get("serving.spec.verify_steps", 0) > 0
        # satellite fix: decode tokens/s accounting counts EMITTED tokens
        # (variable per round), not dispatches — so decode_tokens must be
        # everything emitted past the prefill-produced first token, and
        # exceed the round count when drafts land
        decoded = sum(len(h.tokens) - 1 for h in hs)
        assert d.get("serving.decode_tokens", 0) == decoded
        assert d.get("serving.decode_steps", 0) < decoded
        st = spec.stats()
        assert st["speculative"] is True and st["spec_k"] == 3
        # per-engine tally == this run's global movement (sole spec
        # engine inside the delta window)
        assert st["spec_drafted"] == drafted
        assert 0.0 <= st["spec_acceptance_ema"] <= 1.0
        assert st["spec_yield_ema"] > 0
        assert st["decode_tps_ema"] > 0
        assert 0.0 <= counters.get("serving.spec.acceptance") <= 1.0

    def test_constructor_validation(self):
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        from paddle_tpu.serving import LLMEngine
        target, draft = _models()
        with pytest.raises(ValueError, match="kv_layout"):
            LLMEngine(target, draft_model=draft, kv_layout="slots")
        with pytest.raises(ValueError, match="spec_k"):
            _spec(target, draft, spec_k=0)
        paddle.seed(5)
        other = GPTForCausalLM(GPTConfig(
            vocab_size=32, hidden_size=32, num_layers=1, num_heads=4,
            max_seq_len=32, use_flash_attention=False))
        other.eval()
        with pytest.raises(ValueError, match="vocab"):
            _spec(target, other)


@pytest.mark.slow
class TestFleetChaos:
    def _fleet(self, target, draft, **kw):
        from paddle_tpu.serving import ServingFleet
        kw.setdefault("replicas", 2)
        kw.setdefault("threaded", False)
        kw.setdefault("max_slots", 2)
        kw.setdefault("max_seq_len", 32)
        kw.setdefault("min_bucket", 4)
        kw.setdefault("heartbeat_timeout_s", 30.0)
        return ServingFleet(target, draft_model=draft, spec_k=2,
                            kv_layout="paged", block_size=4,
                            prefill_chunk=8, n_blocks=_nb(kw["max_slots"]),
                            **kw)

    def test_replica_kill_mid_draft_loses_nothing(self):
        """The durability contract survives speculation: a replica crash
        mid-draft replays the request onto a survivor and the delivered
        greedy tokens still match the sequential reference."""
        target, draft = _models()
        rng = np.random.default_rng(6)
        prompts = [rng.integers(0, 64, size=n).tolist() for n in (5, 3)]
        refs = [_ref_generate(target, p, 8) for p in prompts]
        fleet = self._fleet(target, draft)
        before = counters.snapshot()
        hs = [fleet.submit(p, max_new_tokens=8) for p in prompts]
        with faultinject.fault_schedule(f"replica_crash@{hs[0].rid}"):
            fleet.join(hs)
        d = counters.delta(before)
        for h, r in zip(hs, refs):
            assert list(h.tokens) == r, (list(h.tokens), r)
            assert h.finish_reason == "length"
        assert d.get("serving.fleet.lost", 0) == 0
        assert d.get("serving.fleet.respawns", 0) == 1
        assert d.get("serving.fleet.retried", 0) == 1
        # the fleet view rolls up speculative telemetry from the replicas
        st = fleet.stats()
        assert st["spec"]["spec_k"] == 2
        assert st["spec"]["drafted"] > 0
        assert 0.0 <= st["spec"]["acceptance"] <= 1.0
        assert 0.0 <= counters.get("serving.fleet.spec_acceptance") <= 1.0
        fleet.drain()

    def test_no_fault_fleet_identity(self):
        target, draft = _models()
        rng = np.random.default_rng(8)
        prompts = [rng.integers(0, 64, size=n).tolist() for n in (4, 6, 9)]
        refs = [_ref_generate(target, p, 6) for p in prompts]
        fleet = self._fleet(target, draft)
        hs = [fleet.submit(p, max_new_tokens=6) for p in prompts]
        fleet.join(hs)
        for h, r in zip(hs, refs):
            assert list(h.tokens) == r
        fleet.drain()
        assert counters.get("serving.fleet.lost") == 0
