"""Continuous-batching serving engine (paddle_tpu.serving).

The load-bearing contract: LLMEngine output is TOKEN-IDENTICAL to running
each request alone through GPT.generate with the same seed — continuous
batching, slot placement, bucketed prefill, and staggered arrival must be
invisible in the tokens.  Plus the robustness surface: eviction/slot
reuse, EOS/deadline/cancel, backpressure, drain, and the O(log S_max)
prefill-program bound."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.profiler import counters


def _model(**kw):
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=32,
                    use_flash_attention=False, **kw)
    paddle.seed(31)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


def _engine(m, **kw):
    from paddle_tpu.serving import LLMEngine
    kw.setdefault("max_slots", 3)
    kw.setdefault("max_seq_len", 32)
    kw.setdefault("min_bucket", 4)
    return LLMEngine(m, **kw)


def _ref_generate(m, prompt, max_new, **kw):
    """Sequential reference: the request alone through GPT.generate."""
    out = np.asarray(m.generate(paddle.to_tensor(np.asarray([prompt])),
                                max_new_tokens=max_new, **kw).numpy())[0]
    return out[len(prompt):]


def _run(eng, handles, limit=200):
    n = 0
    while not all(h.is_finished for h in handles):
        eng.step()
        n += 1
        assert n < limit, "engine did not converge"
    return n


class TestEngineMatchesGenerate:
    @pytest.mark.parametrize("use_rope", [False, True])
    def test_greedy_token_identical(self, use_rope):
        m = _model(use_rope=use_rope)
        rng = np.random.default_rng(0)
        prompts = [rng.integers(0, 64, size=n).tolist()
                   for n in (5, 3, 9, 6, 11)]
        refs = [_ref_generate(m, p, 6) for p in prompts]
        eng = _engine(m)
        hs = [eng.add_request(p, max_new_tokens=6) for p in prompts]
        _run(eng, hs)
        for h, r in zip(hs, refs):
            assert np.array_equal(h.tokens, r), (h.tokens, list(r))
            assert h.finish_reason == "length"

    def test_sampling_token_identical(self):
        """Per-slot temperature/top-k/top-p + per-request key chain
        reproduce generate's draws exactly (shared serving.sampling)."""
        m = _model()
        rng = np.random.default_rng(1)
        prompts = [rng.integers(0, 64, size=n).tolist() for n in (4, 7, 11)]
        kw = dict(do_sample=True, temperature=0.8, top_k=8, top_p=0.9)
        refs = [_ref_generate(m, p, 5, seed=100 + i, **kw)
                for i, p in enumerate(prompts)]
        eng = _engine(m, max_slots=4)
        hs = [eng.add_request(p, max_new_tokens=5, seed=100 + i, **kw)
              for i, p in enumerate(prompts)]
        _run(eng, hs)
        for h, r in zip(hs, refs):
            assert np.array_equal(h.tokens, r), (h.tokens, list(r))

    def test_staggered_arrivals_identical(self):
        """Requests joining mid-flight decode next to half-finished ones
        and still match their solo trajectories."""
        m = _model()
        rng = np.random.default_rng(2)
        prompts = [rng.integers(0, 64, size=n).tolist()
                   for n in (6, 4, 8, 5)]
        refs = [_ref_generate(m, p, 6) for p in prompts]
        eng = _engine(m, max_slots=4)
        hs = [eng.add_request(prompts[0], max_new_tokens=6)]
        eng.step()
        eng.step()
        hs.append(eng.add_request(prompts[1], max_new_tokens=6))
        eng.step()
        hs += [eng.add_request(p, max_new_tokens=6) for p in prompts[2:]]
        _run(eng, hs)
        for h, r in zip(hs, refs):
            assert np.array_equal(h.tokens, r), (h.tokens, list(r))

    def test_mixed_greedy_and_sampled_slots(self):
        m = _model()
        rng = np.random.default_rng(3)
        pg = rng.integers(0, 64, size=5).tolist()
        ps = rng.integers(0, 64, size=7).tolist()
        ref_g = _ref_generate(m, pg, 5)
        ref_s = _ref_generate(m, ps, 5, do_sample=True, temperature=0.7,
                              top_k=6, seed=9)
        eng = _engine(m)
        hg = eng.add_request(pg, max_new_tokens=5)
        hsmp = eng.add_request(ps, max_new_tokens=5, do_sample=True,
                               temperature=0.7, top_k=6, seed=9)
        _run(eng, [hg, hsmp])
        assert np.array_equal(hg.tokens, ref_g)
        assert np.array_equal(hsmp.tokens, ref_s)


class TestSlots:
    def test_eviction_and_reuse(self):
        """5 requests through 2 slots: slots are freed on finish and
        rehanded; everyone completes with the solo trajectory."""
        m = _model()
        rng = np.random.default_rng(4)
        prompts = [rng.integers(0, 64, size=n).tolist()
                   for n in (5, 3, 7, 4, 6)]
        refs = [_ref_generate(m, p, 4) for p in prompts]
        before = counters.snapshot()
        eng = _engine(m, max_slots=2, queue_size=8)
        hs = [eng.add_request(p, max_new_tokens=4) for p in prompts]
        _run(eng, hs)
        delta = counters.delta(before)
        assert delta.get("serving.evictions", 0) == 5
        assert delta.get("serving.evictions.length", 0) == 5
        for h, r in zip(hs, refs):
            assert np.array_equal(h.tokens, r)
        # all slots free again, occupancy gauge settled at 0
        assert eng.stats()["free_slots"] == 2
        assert counters.get("serving.slot_occupancy") == 0.0

    def test_eos_evicts_early(self):
        m = _model()
        rng = np.random.default_rng(5)
        p = rng.integers(0, 64, size=4).tolist()
        # eos = the 2nd greedily generated token → finishes at its first
        # occurrence (which is index 0 if greedy repeats the token)
        ref = _ref_generate(m, p, 8)
        eos = int(ref[1])
        stop = int(np.flatnonzero(ref == eos)[0])
        eng = _engine(m)
        h = eng.add_request(p, max_new_tokens=8, eos_token_id=eos)
        _run(eng, [h])
        assert h.finish_reason == "eos"
        assert h.tokens == list(map(int, ref[: stop + 1]))
        assert eng.stats()["free_slots"] == eng.max_slots

    def test_deadline_expires_in_queue(self):
        """deadline_s=0 is already past at admission: the request is
        dropped from the queue without ever taking a slot."""
        m = _model()
        rng = np.random.default_rng(6)
        p = rng.integers(0, 64, size=4).tolist()
        eng = _engine(m)
        h = eng.add_request(p, max_new_tokens=20, deadline_s=0.0)
        _run(eng, [h])
        assert h.finish_reason == "deadline"
        assert h.tokens == []
        assert eng.stats()["free_slots"] == eng.max_slots

    def test_deadline_evicts_running_with_partial_output(self):
        m = _model()
        rng = np.random.default_rng(6)
        p = rng.integers(0, 64, size=4).tolist()
        eng = _engine(m)
        h = eng.add_request(p, max_new_tokens=20, deadline_s=60.0)
        eng.step()  # admitted; prefill emits the first token
        first = len(h.tokens)
        assert first >= 1 and h.state == "running"
        h.deadline = 0.0  # force expiry; next sweep evicts
        _run(eng, [h])
        assert h.finish_reason == "deadline"
        assert len(h.tokens) == first  # sweep runs before decode
        assert eng.stats()["free_slots"] == eng.max_slots

    def test_cancel_active_and_queued(self):
        m = _model()
        rng = np.random.default_rng(7)
        prompts = [rng.integers(0, 64, size=4).tolist() for _ in range(3)]
        eng = _engine(m, max_slots=1, queue_size=8)
        h0 = eng.add_request(prompts[0], max_new_tokens=20)
        h1 = eng.add_request(prompts[1], max_new_tokens=4)
        eng.step()
        assert h0.state == "running" and h1.state == "queued"
        h0.cancel()   # active
        h1.cancel()   # still queued
        h2 = eng.add_request(prompts[2], max_new_tokens=3)
        _run(eng, [h0, h1, h2])
        assert h0.finish_reason == "cancelled" and len(h0.tokens) >= 1
        assert h1.finish_reason == "cancelled" and h1.tokens == []
        assert h2.finish_reason == "length" and len(h2.tokens) == 3


class TestRobustness:
    def test_backpressure_nonblocking_raises(self):
        from paddle_tpu.serving import EngineBackpressure
        m = _model()
        eng = _engine(m, max_slots=1, queue_size=2)
        eng.add_request([1, 2, 3], max_new_tokens=4)
        eng.add_request([1, 2, 3], max_new_tokens=4)
        with pytest.raises(EngineBackpressure):
            eng.add_request([1, 2, 3], max_new_tokens=4, block=False)

    def test_backpressure_blocking_times_out(self):
        from paddle_tpu.serving import EngineBackpressure
        m = _model()
        eng = _engine(m, max_slots=1, queue_size=1)
        eng.add_request([1, 2, 3], max_new_tokens=4)
        with pytest.raises(EngineBackpressure, match="timed out"):
            eng.add_request([1, 2, 3], max_new_tokens=4, block=True,
                            timeout=0.05)

    def test_backpressure_releases_as_queue_drains(self):
        from paddle_tpu.serving import EngineBackpressure
        m = _model()
        eng = _engine(m, max_slots=1, queue_size=1)
        h0 = eng.add_request([1, 2, 3], max_new_tokens=2)  # fills queue
        with pytest.raises(EngineBackpressure):
            eng.add_request([2, 3, 4], max_new_tokens=2, block=False)
        eng.step()  # h0 admitted to the slot → queue has room again
        h1 = eng.add_request([2, 3, 4], max_new_tokens=2, block=False)
        _run(eng, [h0, h1])
        assert all(h.finish_reason == "length" for h in (h0, h1))

    def test_drain_finishes_everything_and_closes(self):
        from paddle_tpu.serving import EngineClosed
        m = _model()
        rng = np.random.default_rng(8)
        prompts = [rng.integers(0, 64, size=4).tolist() for _ in range(4)]
        eng = _engine(m, max_slots=2, queue_size=8)
        hs = [eng.add_request(p, max_new_tokens=3) for p in prompts]
        eng.step()
        done = eng.drain()
        assert all(h.is_finished for h in hs)
        assert {r.rid for r in done} | {h.rid for h in hs} \
            == {h.rid for h in hs}
        assert not eng.has_work()
        with pytest.raises(EngineClosed):
            eng.add_request([1, 2], max_new_tokens=2)

    def test_request_validation(self):
        m = _model()
        eng = _engine(m)
        with pytest.raises(ValueError, match="max_seq_len"):
            eng.add_request(list(range(20)), max_new_tokens=20)
        with pytest.raises(ValueError, match="empty"):
            eng.add_request([], max_new_tokens=2)

    def test_streaming_iterator(self):
        m = _model()
        rng = np.random.default_rng(9)
        p = rng.integers(0, 64, size=5).tolist()
        ref = _ref_generate(m, p, 6)
        eng = _engine(m)
        h = eng.add_request(p, max_new_tokens=6)
        streamed = list(h)  # pumps eng.step() internally
        assert np.array_equal(streamed, ref)
        assert np.array_equal(h.output_ids(), list(p) + list(ref))

    def test_queued_deadline_expiry_evicts_before_prefill(self):
        """An expired-deadline request is dropped from the QUEUE — counted
        under serving.deadline_expired, never reaching prefill — while a
        healthy request admitted in the same step is unaffected."""
        m = _model()
        rng = np.random.default_rng(12)
        p_live = rng.integers(0, 64, size=5).tolist()
        ref = _ref_generate(m, p_live, 4)
        eng = _engine(m)
        before = counters.snapshot()
        h_dead = eng.add_request(rng.integers(0, 64, size=4).tolist(),
                                 max_new_tokens=8, deadline_s=0.0)
        h_live = eng.add_request(p_live, max_new_tokens=4)
        _run(eng, [h_dead, h_live])
        d = counters.delta(before)
        assert h_dead.finish_reason == "deadline"
        assert h_dead.tokens == []
        assert h_live.finish_reason == "length"
        assert np.array_equal(h_live.tokens, ref)
        assert d.get("serving.deadline_expired", 0) == 1
        # only the live request ever prefilled (no slot/work for the dead)
        assert d.get("serving.prefill_batches", 0) == 1
        assert eng.stats()["free_slots"] == eng.max_slots

    def test_poisoned_request_contained_to_error(self):
        """A request whose prefill blows up finishes with
        finish_reason="error" (exception on .error) — the slot is returned
        and every OTHER request still matches sequential generate."""
        from paddle_tpu.resilience import faultinject
        m = _model()
        rng = np.random.default_rng(13)
        p_good = rng.integers(0, 64, size=6).tolist()
        ref = _ref_generate(m, p_good, 4)
        eng = _engine(m)
        h_bad = eng.add_request(rng.integers(0, 64, size=4).tolist(),
                                max_new_tokens=8)   # rid 0
        h_good = eng.add_request(p_good, max_new_tokens=4)  # rid 1
        before = counters.snapshot()
        with faultinject.fault_schedule(f"serving_prefill@{h_bad.rid}"):
            _run(eng, [h_bad, h_good])
            assert faultinject.fired == [("serving_prefill", h_bad.rid)]
        d = counters.delta(before)
        assert h_bad.finish_reason == "error"
        assert isinstance(h_bad.error, faultinject.InjectedFault)
        assert h_bad.tokens == []
        assert h_good.finish_reason == "length"
        assert np.array_equal(h_good.tokens, ref)
        assert d.get("serving.request_errors", 0) == 1
        assert eng.stats()["free_slots"] == eng.max_slots
        # the engine keeps serving after containment
        h_next = eng.add_request(p_good, max_new_tokens=4)
        _run(eng, [h_next])
        assert np.array_equal(h_next.tokens, ref)


class TestBuckets:
    def test_bucket_length(self):
        from paddle_tpu.serving import bucket_length
        assert bucket_length(1, min_bucket=4) == 4
        assert bucket_length(4, min_bucket=4) == 4
        assert bucket_length(5, min_bucket=4) == 8
        assert bucket_length(9, min_bucket=4) == 16
        assert bucket_length(9, min_bucket=4, max_len=12) == 12

    def test_prefill_programs_bounded_and_no_steady_retraces(self):
        """Many distinct prompt lengths → O(log S_max) prefill programs;
        once buckets are warm, new requests trace NOTHING."""
        m = _model()
        rng = np.random.default_rng(10)
        eng = _engine(m, max_slots=2, queue_size=32)
        lens = [3, 4, 5, 6, 7, 9, 11, 13, 15]  # buckets {4, 8, 16}
        hs = [eng.add_request(rng.integers(0, 64, size=n).tolist(),
                              max_new_tokens=2) for n in lens]
        _run(eng, hs)
        assert eng.stats()["prefill_programs"] == 3
        assert counters.get("serving.prefill_programs") == 3
        # steady state: same buckets again — zero serving retraces
        before = counters.snapshot()
        hs = [eng.add_request(rng.integers(0, 64, size=n).tolist(),
                              max_new_tokens=2) for n in (3, 6, 12)]
        _run(eng, hs)
        delta = counters.delta(before)
        assert delta.get("serving.retraces", 0) == 0, delta
        assert delta.get("jit.traces", 0) == 0
        assert eng.stats()["prefill_programs"] == 3


class TestGenerateExtensions:
    def test_engine_generate_blocking_api(self):
        m = _model()
        rng = np.random.default_rng(11)
        prompts = [rng.integers(0, 64, size=n).tolist() for n in (4, 6, 3)]
        refs = [_ref_generate(m, p, 4) for p in prompts]
        eng = _engine(m, max_slots=2, queue_size=2)  # oversubscribed
        outs = eng.generate(prompts, max_new_tokens=4)
        for o, p, r in zip(outs, prompts, refs):
            assert np.array_equal(o, list(p) + list(r))

    def test_generation_predictor_routes_through_engine(self):
        from paddle_tpu.inference import GenerationPredictor
        m = _model()
        rng = np.random.default_rng(12)
        prompts = [rng.integers(0, 64, size=n).tolist() for n in (5, 7)]
        refs = [_ref_generate(m, p, 4) for p in prompts]
        pred = GenerationPredictor(m, max_slots=2, max_seq_len=32,
                                   min_bucket=4)
        outs = pred.generate(prompts, max_new_tokens=4)
        for o, p, r in zip(outs, prompts, refs):
            assert np.array_equal(o, list(p) + list(r))
        streamed = list(pred.stream(prompts[0], max_new_tokens=4))
        assert np.array_equal(streamed, refs[0])
        pred.close()
        from paddle_tpu.serving import EngineClosed
        with pytest.raises(EngineClosed):
            pred.engine.add_request([1], max_new_tokens=1)

    def test_generate_top_p_reproducible_and_constraining(self):
        """top_p in GPT.generate: seeded reproducibility; p→0 degenerates
        to greedy (nucleus keeps only the top token)."""
        m = _model()
        ids = paddle.randint(0, 64, [2, 4])
        a = np.asarray(m.generate(ids, max_new_tokens=5, do_sample=True,
                                  top_p=0.7, seed=3).numpy())
        b = np.asarray(m.generate(ids, max_new_tokens=5, do_sample=True,
                                  top_p=0.7, seed=3).numpy())
        assert np.array_equal(a, b)
        greedy = np.asarray(m.generate(ids, max_new_tokens=5).numpy())
        tiny = np.asarray(m.generate(ids, max_new_tokens=5, do_sample=True,
                                     top_p=1e-6, seed=5).numpy())
        assert np.array_equal(tiny, greedy)

    def test_gen_cache_lru_bound(self):
        """_gen_cache is LRU-bounded: recently used shapes survive, the
        stalest executable is evicted."""
        m = _model()
        m._gen_cache_max = 2
        ids3 = paddle.randint(0, 64, [1, 3])
        ids4 = paddle.randint(0, 64, [1, 4])
        ids5 = paddle.randint(0, 64, [1, 5])
        m.generate(ids3, max_new_tokens=2)   # A
        m.generate(ids4, max_new_tokens=2)   # B
        assert len(m._gen_cache) == 2
        m.generate(ids3, max_new_tokens=2)   # hit A → B is now LRU
        m.generate(ids5, max_new_tokens=2)   # C evicts B
        keys = list(m._gen_cache)
        assert len(keys) == 2
        assert {k[1] for k in keys} == {3, 5}

    def test_moe_model_serves(self):
        m = _model(num_experts=2)
        eng = _engine(m)
        h = eng.add_request([1, 2, 3, 4], max_new_tokens=3)
        _run(eng, [h])
        assert len(h.tokens) == 3


class TestFleetSatellites:
    """Engine-level guarantees the elastic fleet layer builds on:
    finish-CAS idempotence, atomic stats with outstanding-token
    accounting, structured backpressure, and drain's pre-prefill sweep
    of deadline-expired queued requests."""

    def test_double_finish_is_idempotent_single_eviction(self):
        """The fleet reaps/cancels from a different thread than the
        replica's step loop: a racing double finish must transition once,
        keep the first reason, and never double-release the KV slot."""
        m = _model()
        eng = _engine(m)
        h = eng.add_request([1, 2, 3], max_new_tokens=4, block=False)
        eng.step()                      # admitted: slot assigned
        assert h.slot is not None
        before = counters.snapshot()
        events = []
        assert eng._finish(h, "cancelled", events) is True
        free0 = eng.stats()["free_slots"]
        assert eng._finish(h, "error", events) is False   # CAS loses
        assert h.finish_reason == "cancelled"             # first wins
        assert eng.stats()["free_slots"] == free0
        assert sorted(eng._free) == sorted(set(eng._free))
        d = counters.delta(before)
        assert d.get("serving.evictions", 0) == 1
        assert len(events) == 1
        eng.drain()

    def test_stats_outstanding_tokens_and_tps_ema(self):
        """stats() is one atomic snapshot; outstanding_tokens is the
        undelivered decode-token backlog (+max_new at admission, -1 per
        emitted token, -remainder at finish) and sums back to zero."""
        m = _model()
        eng = _engine(m)
        assert eng.stats()["outstanding_tokens"] == 0
        h1 = eng.add_request([1, 2, 3], max_new_tokens=6, block=False)
        h2 = eng.add_request([4, 5], max_new_tokens=3, block=False)
        assert eng.stats()["outstanding_tokens"] == 9
        eng.step()
        delivered = len(h1.tokens) + len(h2.tokens)
        assert eng.stats()["outstanding_tokens"] == 9 - delivered
        _run(eng, [h1, h2])
        st = eng.stats()
        assert st["outstanding_tokens"] == 0
        assert st["decode_tps_ema"] > 0        # decode launches ran
        # early finish returns the unspent budget, not just -1 per token
        h3 = eng.add_request([1, 2, 3], max_new_tokens=20, block=False)
        eng.step()
        h3.cancel()
        _run(eng, [h3])
        assert eng.stats()["outstanding_tokens"] == 0
        eng.drain()

    def test_backpressure_carries_depth_and_hint(self):
        from paddle_tpu.serving import EngineBackpressure
        m = _model()
        eng = _engine(m, max_slots=1, queue_size=2)
        hs = [eng.add_request([1, 2, 3], max_new_tokens=4, block=False)
              for _ in range(2)]
        with pytest.raises(EngineBackpressure) as ei:
            eng.add_request([1, 2, 3], max_new_tokens=4, block=False)
        assert ei.value.queue_depth == 2
        assert ei.value.retry_after_hint is None   # cold: no EMA yet
        _run(eng, hs)
        hs = [eng.add_request([1, 2, 3], max_new_tokens=4, block=False)
              for _ in range(2)]
        with pytest.raises(EngineBackpressure) as ei:
            eng.add_request([1, 2, 3], max_new_tokens=4, block=False)
        assert ei.value.queue_depth == 2
        assert ei.value.retry_after_hint is not None   # backlog / tps EMA
        assert ei.value.retry_after_hint > 0
        _run(eng, hs)
        eng.drain()

    def test_drain_sweeps_expired_queued_without_prefill(self):
        """drain() sweeps deadline-expired queued requests BEFORE the
        step loop: they terminate with reason='deadline' and zero tokens
        instead of spending a prefill launch each."""
        m = _model()
        eng = _engine(m, max_slots=1)
        h1 = eng.add_request([1, 2, 3], max_new_tokens=3, block=False)
        h2 = eng.add_request([4, 5, 6], max_new_tokens=3, block=False,
                             deadline_s=0.0)
        before = counters.snapshot()
        eng.drain()
        d = counters.delta(before)
        assert h1.finish_reason == "length"
        assert h2.finish_reason == "deadline"
        assert h2.tokens == []
        assert d.get("serving.deadline_expired", 0) == 1
        assert d.get("serving.prefill_batches", 0) == 1   # h1 only
