"""Multi-tenant LoRA adapter serving (paddle_tpu.serving.adapters).

The load-bearing contracts: (1) base rows through an adapter engine are
BITWISE identical to an adapter-free engine — slot 0 selects the
un-adapted activations themselves, not ``y + 0``; (2) a heterogeneous
batch (several tenants + base in the same decode step) is
TOKEN-IDENTICAL to running each tenant sequentially — adapter ids are
operands, one compiled program serves any tenant mix; (3) the
AdapterArena is exact bookkeeping: LRU eviction only ever takes
refcount-0 slots, refcounts reconcile to zero after churn, exhaustion
defers admission (nothing allocated) exactly like KV-pool exhaustion;
(4) the per-tenant prefix-cache planes never leak KV across tenants
(KV computed under an adapter is NOT base KV for the same tokens);
(5) the whole thing composes with int8 weights, speculative decoding
(draft on base, verify under the target's adapter) and a mesh(1,1)
arena without changing a single emitted token."""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.profiler import counters
from paddle_tpu.resilience import faultinject
from paddle_tpu.serving.adapters import (AdapterArenaExhausted,
                                         random_lora_factors)

_MODEL = None
_CFG = None


def _model():
    """Module-cached tiny GPT (the adapter math is size-independent)."""
    global _MODEL, _CFG
    if _MODEL is None:
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        _CFG = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                         num_heads=4, max_seq_len=32,
                         use_flash_attention=False)
        paddle.seed(31)
        _MODEL = GPTForCausalLM(_CFG)
        _MODEL.eval()
    return _MODEL


def _cfg():
    _model()
    return _CFG


def _paged(m, **kw):
    from paddle_tpu.serving import LLMEngine
    kw.setdefault("max_slots", 3)
    kw.setdefault("max_seq_len", 32)
    kw.setdefault("min_bucket", 4)
    kw.setdefault("block_size", 4)
    kw.setdefault("prefill_chunk", 8)
    return LLMEngine(m, kv_layout="paged", **kw)


def _adapter_engine(m, slots=3, rank=4, **kw):
    return _paged(m, adapter_slots=slots, adapter_rank=rank, **kw)


# scale=1.0 so every tenant visibly flips the greedy argmax of the tiny
# random model (the arena math is scale-linear; tests need divergence)
def _factors(seed, rank=3):
    return random_lora_factors(_cfg(), rank, seed=seed, scale=1.0)


def _run(eng, handles, limit=400):
    n = 0
    while not all(h.is_finished for h in handles):
        eng.step()
        n += 1
        assert n < limit, "engine did not converge"
    return [list(map(int, h.tokens)) for h in handles]


def _arena_reconciles(eng):
    """Every tenant pin released, resident <= slots, free+resident
    accounts for every slot."""
    st = eng.adapters.stats()
    return (all(r == 0 for r in st["tenants"].values())
            and st["resident"] <= st["slots"])


class TestValidationAndFactors:
    def test_adapter_slots_requires_paged_layout(self):
        with pytest.raises(ValueError, match="adapter_slots"):
            from paddle_tpu.serving import LLMEngine
            LLMEngine(_model(), kv_layout="slots", max_slots=2,
                      max_seq_len=32, adapter_slots=2)

    def test_adapter_request_on_adapter_free_engine_refused(self):
        eng = _paged(_model())
        with pytest.raises(ValueError, match="adapter"):
            eng.add_request([1, 2, 3], max_new_tokens=2, adapter="t1")

    def test_unregistered_tenant_refused_at_admission(self):
        eng = _adapter_engine(_model(), slots=2)
        with pytest.raises(KeyError):
            eng.add_request([1, 2, 3], max_new_tokens=2, adapter="ghost")

    def test_rank_overflow_refused(self):
        eng = _adapter_engine(_model(), slots=2, rank=4)
        with pytest.raises(ValueError, match="expects"):
            eng.register_adapter("fat", _factors(1, rank=8))

    def test_factor_shapes_cover_all_four_projections(self):
        f = _factors(0, rank=3)
        c = _cfg()
        H, F, L = c.hidden_size, 4 * c.hidden_size, c.num_layers
        assert f["a_qkv_w"].shape == (L, H, 3)
        assert f["b_qkv_w"].shape == (L, 3, 3 * H)
        assert f["a_fc1_w"].shape == (L, H, 3)
        assert f["b_fc1_w"].shape == (L, 3, F)
        assert f["a_fc2_w"].shape == (L, F, 3)
        assert f["b_fc2_w"].shape == (L, 3, H)
        assert f["a_proj_w"].shape == (L, H, 3)
        assert f["b_proj_w"].shape == (L, 3, H)


class TestBasePassthrough:
    @pytest.mark.slow  # tier-1 passthrough coverage: check_counters base-row gate
    def test_slot0_logits_bitwise_identical_at_model_level(self):
        """The gathered-LoRA program with adapter id 0 returns the
        un-adapted logits THEMSELVES (jnp.where selects y, not y + 0)."""
        import jax.numpy as jnp
        m = _model()
        eng = _adapter_engine(m, slots=2, rank=4)
        eng.register_adapter("t1", _factors(1))
        with eng._cond:
            s = eng.adapters.acquire("t1")
        slabs = eng.adapters.slabs()
        w = eng._w
        ids = np.zeros((1, 8), np.int32)
        ids[0, :5] = [1, 2, 3, 4, 5]
        bt = np.asarray([1, 2, 0, 0, 0, 0, 0, 0], np.int32)
        pk = jnp.zeros_like(eng._pk)
        pv = jnp.zeros_like(eng._pv)
        _, _, plain = m.prefill_paged(w, ids, np.int32(0), np.int32(5),
                                      bt, pk, pv)
        _, _, base = m.prefill_paged(w, ids, np.int32(0), np.int32(5),
                                     bt, pk, pv, adapters=slabs,
                                     adapter_ids=np.asarray([0], np.int32))
        _, _, adapted = m.prefill_paged(w, ids, np.int32(0), np.int32(5),
                                        bt, pk, pv, adapters=slabs,
                                        adapter_ids=np.asarray([s],
                                                               np.int32))
        assert bool(jnp.all(base == plain))           # bitwise, not close
        assert float(jnp.abs(adapted - base).max()) > 0
        with eng._cond:
            eng.adapters.release("t1")
        eng.release_kv()

    @pytest.mark.slow  # two engine builds; model-level bitwise test covers tier-1
    def test_base_rows_token_identical_to_adapter_free_engine(self):
        m = _model()
        prompts = [[1, 2, 3, 4, 5], [9, 8, 7], [3, 1, 4, 1, 5, 9, 2, 6]]
        ref_eng = _paged(m)
        refs = _run(ref_eng, [ref_eng.add_request(p, max_new_tokens=6,
                                                  seed=i)
                              for i, p in enumerate(prompts)])
        eng = _adapter_engine(m, slots=2, rank=4)
        eng.register_adapter("t1", _factors(1))
        outs = _run(eng, [eng.add_request(p, max_new_tokens=6, seed=i)
                          for i, p in enumerate(prompts)])
        assert outs == refs
        ref_eng.release_kv()
        eng.release_kv()


class TestMixedTenantIdentity:
    @pytest.mark.slow  # tier-1 identity coverage: check_counters adapters phase
    def test_heterogeneous_batch_matches_per_tenant_sequential(self):
        """Three tenants + a base row decoding in the SAME batch emit
        exactly the tokens each tenant gets running alone — adapter ids
        are row operands, not program shapes."""
        m = _model()
        prompt = [1, 2, 3, 4, 5]
        fs = {t: _factors(i + 1) for i, t in enumerate(("t1", "t2", "t3"))}

        eng = _adapter_engine(m, slots=3, rank=4, max_slots=4)
        for t, f in fs.items():
            eng.register_adapter(t, f)
        hs = [eng.add_request(prompt, max_new_tokens=6)]
        hs += [eng.add_request(prompt, max_new_tokens=6, adapter=t)
               for t in ("t1", "t2", "t3")]
        base, g1, g2, g3 = _run(eng, hs)
        assert _arena_reconciles(eng)
        eng.release_kv()

        # base row == adapter-free engine; tenants all diverge pairwise
        ref_eng = _paged(m)
        [ref] = _run(ref_eng, [ref_eng.add_request(prompt,
                                                   max_new_tokens=6)])
        ref_eng.release_kv()
        assert base == ref
        assert len({tuple(g1), tuple(g2), tuple(g3), tuple(base)}) == 4

        # sequential per-tenant runs on a fresh engine
        seq = _adapter_engine(m, slots=3, rank=4)
        for t, f in fs.items():
            seq.register_adapter(t, f)
        for t, mixed in (("t1", g1), ("t2", g2), ("t3", g3)):
            [alone] = _run(seq, [seq.add_request(prompt, max_new_tokens=6,
                                                 adapter=t)])
            assert alone == mixed, t
        seq.release_kv()

    def test_prefix_cache_never_leaks_kv_across_tenants(self):
        """Same prompt, tenant after tenant on ONE engine: each tenant's
        donated prefix lives in its own key plane, so t2 re-prefills
        under ITS adapter instead of adopting t1's KV — and a same-tenant
        rerun still gets the warm prefix hit."""
        m = _model()
        prompt = [1, 2, 3, 4, 5, 6, 7, 8]
        eng = _adapter_engine(m, slots=2, rank=4)
        eng.register_adapter("t1", _factors(1))
        eng.register_adapter("t2", _factors(2))
        [g1] = _run(eng, [eng.add_request(prompt, max_new_tokens=5,
                                          adapter="t1")])
        [g2] = _run(eng, [eng.add_request(prompt, max_new_tokens=5,
                                          adapter="t2")])
        before = counters.get("serving.kv.prefix_hits")
        [g1b] = _run(eng, [eng.add_request(prompt, max_new_tokens=5,
                                           adapter="t1")])
        warm_hits = counters.get("serving.kv.prefix_hits") - before
        eng.release_kv()

        # isolated single-tenant engines as ground truth
        for t, got in (("t1", g1), ("t2", g2)):
            solo = _adapter_engine(m, slots=2, rank=4)
            solo.register_adapter(t, _factors(1 if t == "t1" else 2))
            [want] = _run(solo, [solo.add_request(prompt, max_new_tokens=5,
                                                  adapter=t)])
            solo.release_kv()
            assert got == want, t
        assert g1b == g1
        assert warm_hits >= 1                 # same-tenant reuse intact


class TestArenaAccounting:
    def test_lru_eviction_takes_only_refcount_zero_slots(self):
        eng = _adapter_engine(_model(), slots=2, rank=4)
        for i, t in enumerate(("t1", "t2", "t3")):
            eng.register_adapter(t, _factors(i + 1))
        ad = eng.adapters
        with eng._cond:
            s1 = ad.acquire("t1")
            s2 = ad.acquire("t2")
            assert s1 != s2 and s1 > 0 and s2 > 0
            # arena full, both pinned: a third tenant cannot land
            with pytest.raises(AdapterArenaExhausted):
                ad.acquire("t3")
            ad.release("t1")                  # refcount 0, stays resident
            s3 = ad.acquire("t3")             # evicts t1 (the only LRU)
            assert s3 == s1
            st = ad.stats()
            assert st["evictions"] == 1
            assert set(st["tenants"]) == {"t2", "t3"}
            # re-acquiring the survivor is a warm hit, refcount 2
            assert ad.acquire("t2") == s2
            assert ad.stats()["tenants"]["t2"] == 2
            ad.release("t2")
            ad.release("t2")
            ad.release("t3")
            with pytest.raises(ValueError):   # refcount underflow
                ad.release("t2")
        eng.release_kv()

    def test_register_refuses_pinned_tenant_and_updates_idle(self):
        eng = _adapter_engine(_model(), slots=2, rank=4)
        eng.register_adapter("t1", _factors(1))
        ad = eng.adapters
        with eng._cond:
            ad.acquire("t1")
            with pytest.raises(ValueError, match="referenced"):
                ad.register("t1", _factors(7))
            ad.release("t1")
            ad.register("t1", _factors(7))    # idle: hot-swap allowed
        eng.release_kv()

    def test_refcounts_reconcile_after_churn(self):
        m = _model()
        rng = np.random.default_rng(5)
        eng = _adapter_engine(m, slots=2, rank=4)
        for i, t in enumerate(("t1", "t2", "t3")):
            eng.register_adapter(t, _factors(i + 1))
        tenants = [None, "t1", "t2", "t3", "t1", None, "t3", "t2"]
        hs = [eng.add_request(rng.integers(0, 64, size=4).tolist(),
                              max_new_tokens=3, seed=i, adapter=t)
              for i, t in enumerate(tenants)]
        _run(eng, hs)
        st = eng.adapters.stats()
        assert _arena_reconciles(eng)
        assert st["loads"] >= 3               # every tenant paged in
        assert st["evictions"] >= 1           # 3 tenants through 2 slots
        eng.release_kv()


class TestExhaustionBackpressure:
    @pytest.mark.slow  # serial 1-slot arena churn (several prefill compiles)
    def test_arena_exhaustion_defers_like_kv_exhaustion(self):
        """Two tenants through a ONE-slot arena: the second request
        parks at the queue head with nothing allocated, admits once the
        first finishes (evicting its idle adapter), both token-exact."""
        m = _model()
        eng = _adapter_engine(m, slots=1, rank=4, max_slots=2)
        eng.register_adapter("t1", _factors(1))
        eng.register_adapter("t2", _factors(2))
        h1 = eng.add_request([1, 2, 3, 4], max_new_tokens=5, adapter="t1")
        h2 = eng.add_request([1, 2, 3, 4], max_new_tokens=5, adapter="t2")
        g1, g2 = _run(eng, [h1, h2])
        st = eng.adapters.stats()
        assert st["exhausted"] >= 1
        assert st["evictions"] >= 1
        assert _arena_reconciles(eng)
        eng.release_kv()
        for t, got in (("t1", g1), ("t2", g2)):
            solo = _adapter_engine(m, slots=1, rank=4)
            solo.register_adapter(t, _factors(1 if t == "t1" else 2))
            [want] = _run(solo, [solo.add_request([1, 2, 3, 4],
                                                  max_new_tokens=5,
                                                  adapter=t)])
            solo.release_kv()
            assert got == want, t

    def test_injected_load_drop_is_deterministic_and_clean(self):
        """adapter_load_drop at a specific admission: the slot is handed
        back BEFORE any slab write, the request defers queued-with-
        backoff and retries to the SAME tokens — never another tenant's
        weights."""
        m = _model()
        eng = _adapter_engine(m, slots=2, rank=4)
        eng.register_adapter("t1", _factors(1))
        before = counters.snapshot()
        h0 = eng.add_request([5, 6, 7], max_new_tokens=4, seed=0)
        rid = h0.rid + 1
        with faultinject.fault_schedule(f"adapter_load_drop@{rid}"):
            h1 = eng.add_request([1, 2, 3, 4], max_new_tokens=4,
                                 adapter="t1")
            _run(eng, [h0, h1])
            assert ("adapter_load_drop", rid) in faultinject.fired
        d = counters.delta(before)
        assert d.get("serving.adapter.load_drops", 0) == 1
        st = eng.adapters.stats()
        assert st["load_drops"] == 1
        assert _arena_reconciles(eng)
        g1 = list(map(int, h1.tokens))
        eng.release_kv()
        solo = _adapter_engine(m, slots=2, rank=4)
        solo.register_adapter("t1", _factors(1))
        [want] = _run(solo, [solo.add_request([1, 2, 3, 4],
                                              max_new_tokens=4,
                                              adapter="t1")])
        solo.release_kv()
        assert g1 == want


class TestComposition:
    @pytest.mark.slow  # int8 engine build (quantized program set compiles)
    def test_int8_base_weights_compose(self):
        """Adapters ride BESIDE the int8 dequant epilogue: base rows
        match the int8 adapter-free engine, tenant rows diverge and
        match the tenant alone."""
        m = _model()
        prompt = [2, 4, 6, 8, 10]
        ref = _paged(m, weight_dtype="int8")
        [base_ref] = _run(ref, [ref.add_request(prompt, max_new_tokens=5)])
        ref.release_kv()
        eng = _adapter_engine(m, slots=2, rank=4, weight_dtype="int8")
        eng.register_adapter("t1", _factors(1))
        hb = eng.add_request(prompt, max_new_tokens=5)
        h1 = eng.add_request(prompt, max_new_tokens=5, adapter="t1")
        base, g1 = _run(eng, [hb, h1])
        eng.release_kv()
        assert base == base_ref
        assert g1 != base
        solo = _adapter_engine(m, slots=2, rank=4, weight_dtype="int8")
        solo.register_adapter("t1", _factors(1))
        [want] = _run(solo, [solo.add_request(prompt, max_new_tokens=5,
                                              adapter="t1")])
        solo.release_kv()
        assert g1 == want

    @pytest.mark.slow  # draft+target engine pair (two program sets compile)
    def test_speculative_verify_under_tenant_adapter(self):
        """Draft proposes on the BASE model, verification runs under the
        target's adapter — greedy output is token-identical to the
        non-speculative adapter engine for base AND tenant rows."""
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        from paddle_tpu.serving.kvcache import blocks_for_tokens
        m = _model()
        paddle.seed(7)
        draft = GPTForCausalLM(GPTConfig(vocab_size=64, hidden_size=32,
                                         num_layers=1, num_heads=4,
                                         max_seq_len=32,
                                         use_flash_attention=False))
        draft.eval()
        prompt = [1, 2, 3, 4, 5]
        plain = _adapter_engine(m, slots=2, rank=4)
        plain.register_adapter("t1", _factors(1))
        want = _run(plain, [plain.add_request(prompt, max_new_tokens=6),
                            plain.add_request(prompt, max_new_tokens=6,
                                              adapter="t1")])
        plain.release_kv()
        nb = 2 * 3 * blocks_for_tokens(32, 4) + 1
        spec = _adapter_engine(m, slots=2, rank=4, draft_model=draft,
                               spec_k=3, n_blocks=nb)
        spec.register_adapter("t1", _factors(1))
        got = _run(spec, [spec.add_request(prompt, max_new_tokens=6),
                          spec.add_request(prompt, max_new_tokens=6,
                                           adapter="t1")])
        st = spec.stats()
        spec.release_kv()
        assert got == want
        assert st["speculative"] is True
        assert _arena_reconciles(plain) or True   # released above

    @pytest.mark.slow  # mesh(1,1) engine build; parity also tier-1 in test_serving_mesh
    def test_mesh1_arena_is_invisible(self):
        """A mesh(1,1) adapter engine emits the same tokens as the
        meshless one — the StateArena spec layer stays transparent."""
        import jax
        from jax.sharding import Mesh
        if jax.device_count() < 1:
            pytest.skip("no devices")
        mesh = Mesh(np.array(jax.devices()[:1]).reshape(1), ("mp",))
        m = _model()
        prompt = [3, 5, 7, 9]
        plain = _adapter_engine(m, slots=2, rank=4)
        plain.register_adapter("t1", _factors(1))
        want = _run(plain, [plain.add_request(prompt, max_new_tokens=5),
                            plain.add_request(prompt, max_new_tokens=5,
                                              adapter="t1")])
        plain.release_kv()
        meshed = _adapter_engine(m, slots=2, rank=4, mesh=mesh)
        meshed.register_adapter("t1", _factors(1))
        got = _run(meshed, [meshed.add_request(prompt, max_new_tokens=5),
                            meshed.add_request(prompt, max_new_tokens=5,
                                               adapter="t1")])
        meshed.release_kv()
        assert got == want


class TestTenantTelemetry:
    def test_engine_emits_per_tenant_bucket_histograms(self):
        """Adapter engines mirror TTFT/ITL into tenant-bucket histograms
        — ``base`` for un-adapted rows, a stable crc32 bucket for
        tenants — feeding the noisy_neighbor watchdog."""
        m = _model()
        eng = _adapter_engine(m, slots=2, rank=4)
        eng.register_adapter("t1", _factors(1))
        _run(eng, [eng.add_request([1, 2, 3], max_new_tokens=3),
                   eng.add_request([4, 5, 6], max_new_tokens=3,
                                   adapter="t1")])
        names = set(eng.histogram_snapshot())
        eng.release_kv()
        assert "serving.ttft_ns.tenant.base" in names
        assert "serving.itl_ns.tenant.base" in names
        tenant = {n for n in names
                  if n.startswith("serving.itl_ns.tenant.t")}
        assert len(tenant) == 1           # t1 hashed into one bucket
        # the same names reach the PROCESS registry the health plane
        # snapshots (observe() writes both)
        from paddle_tpu.profiler import metrics
        assert set(tenant) <= set(metrics.histograms())

    def test_noisy_neighbor_watchdog_fires_on_tenant_skew(self):
        """One tenant bucket's windowed ITL p95 at >= 4x the median of
        the others fires; balanced traffic or single-bucket windows
        never do."""
        from paddle_tpu.profiler import health
        from paddle_tpu.profiler.health import Snapshot, Window
        from paddle_tpu.profiler.metrics import Histogram
        wd = [w for w in health.default_watchdogs()
              if w.name == "noisy_neighbor"][0]

        def snap(ts, specs):
            hists = {}
            for name, values in specs.items():
                h = Histogram(name, "ns")
                for v in values:
                    h.record(v)
                hists[name] = h
            return Snapshot(ts, 0, {}, hists)

        b = "serving.itl_ns.tenant.base"
        t = "serving.itl_ns.tenant.t3"
        # balanced: both buckets at ~1ms → quiet
        w = Window(snap(0.0, {}),
                   snap(1.0, {b: [1e6] * 10, t: [1e6] * 10}))
        firing, _ = wd.fn(w, None)
        assert not firing
        # skewed: t3 at 20ms vs base at 1ms → fires with detail
        w = Window(snap(0.0, {}),
                   snap(1.0, {b: [1e6] * 10, t: [20e6] * 10}))
        firing, detail = wd.fn(w, None)
        assert firing
        assert detail["worst_bucket"] == "t3"
        assert detail["buckets"] == 2
        # single bucket (no neighbor to compare): abstains
        w = Window(snap(0.0, {}), snap(1.0, {t: [20e6] * 10}))
        firing, _ = wd.fn(w, None)
        assert not firing
        # thin traffic (< 8 samples in a bucket): abstains
        w = Window(snap(0.0, {}),
                   snap(1.0, {b: [1e6] * 10, t: [20e6] * 3}))
        firing, _ = wd.fn(w, None)
        assert not firing


class TestFleetAdapters:
    def test_fleet_roll_up_and_chaos_load_drop(self):
        """Fleet-level contract: registry replays onto every replica,
        per-tenant traffic finishes token-exact under an injected
        adapter_load_drop, and stats() rolls the arenas up."""
        from paddle_tpu.serving import ServingFleet
        m = _model()
        prompt = [1, 2, 3, 4, 5]
        solo = _adapter_engine(m, slots=2, rank=4)
        solo.register_adapter("t1", _factors(1))
        solo.register_adapter("t2", _factors(2))
        want = _run(solo, [solo.add_request(prompt, max_new_tokens=4),
                           solo.add_request(prompt, max_new_tokens=4,
                                            adapter="t1"),
                           solo.add_request(prompt, max_new_tokens=4,
                                            adapter="t2")])
        solo.release_kv()
        with ServingFleet(m, replicas=2, threaded=False, max_slots=2,
                          max_seq_len=32, min_bucket=4, queue_size=16,
                          kv_layout="paged", block_size=4,
                          prefill_chunk=8, heartbeat_timeout_s=30.0,
                          adapter_slots=2, adapter_rank=4) as fleet:
            fleet.register_adapter("t1", _factors(1))
            fleet.register_adapter("t2", _factors(2))
            with pytest.raises(KeyError):
                fleet.submit(prompt, max_new_tokens=4, adapter="ghost")
            hb = fleet.submit(prompt, max_new_tokens=4)
            h1 = fleet.submit(prompt, max_new_tokens=4, adapter="t1")
            # chaos: drop t2's adapter page-in at its engine admission
            h2 = fleet.submit(prompt, max_new_tokens=4, adapter="t2")
            erid = h2._er.rid
            with faultinject.fault_schedule(f"adapter_load_drop@{erid}"):
                n = 0
                while any(not h.is_finished for h in (hb, h1, h2)):
                    fleet.pump()
                    n += 1
                    assert n < 500
            st = fleet.stats()
            assert [list(map(int, h.tokens)) for h in (hb, h1, h2)] \
                == want
            ad = st["adapters"]
            # slots sum across replicas (fleet-wide arena capacity)
            assert ad["slots"] == 4 and ad["registered"] == 2
            assert ad["loads"] >= 2
            assert all(info["refs"] == 0
                       for info in ad["tenants"].values())
        assert counters.get("serving.fleet.lost") == 0

    def test_router_tenant_affinity_counts_adapter_routed(self):
        """Same-tenant traffic gravitates to the replica already holding
        the adapter (the peek bonus) and counts adapter_routed."""
        from paddle_tpu.serving import ServingFleet
        m = _model()
        with ServingFleet(m, replicas=2, threaded=False, max_slots=2,
                          max_seq_len=32, min_bucket=4, queue_size=16,
                          kv_layout="paged", block_size=4,
                          prefill_chunk=8, heartbeat_timeout_s=30.0,
                          adapter_slots=2, adapter_rank=4) as fleet:
            fleet.register_adapter("t1", _factors(1))
            h1 = fleet.submit([1, 2, 3], max_new_tokens=3, adapter="t1")
            fleet.join([h1])
            before = counters.get("serving.fleet.adapter_routed")
            h2 = fleet.submit([4, 5, 6], max_new_tokens=3, adapter="t1")
            fleet.join([h2])
            assert h2.replica_idx == h1.replica_idx
            assert counters.get("serving.fleet.adapter_routed") \
                - before >= 1
