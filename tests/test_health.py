"""Health plane (profiler.health): windowed signals, SLO burn-rate
alerting, invariant watchdogs, alert lifecycle, and the live wiring.

The load-bearing contracts:

  * window math — snapshot deltas/rates are counter-reset safe and
    histogram windows are element-wise bucket subtraction
    (``Histogram.delta``), so a window percentile reflects ONLY the
    samples recorded inside the window;
  * burn-rate SLOs — an alert needs EVERY configured window burning
    (fast = still happening, slow = sustained), fires once (dedupe),
    writes one flight bundle naming the rule + window, and resolves when
    the measured burn drops;
  * watchdogs — each offline check_counters invariant promoted to a live
    rule fires on its violation and stays silent on a clean run;
  * chaos — ``slow_decode`` fires exactly ``itl_burn`` on a real fleet
    and ``kv_pool_exhausted`` fires exactly ``kv_backpressure`` on a
    paged engine, each leaving a postmortem dump naming the rule;
  * zero-overhead off — with ``FLAGS_health`` off (the default), ticks
    are no-ops and NO counter moves;
  * ops — ``/alerts``, ``/slo``, ``/signals`` serve live JSON and
    ``/healthz`` degrades while an alert fires.
"""

import json
import urllib.error
import urllib.request

import pytest

import paddle_tpu as paddle
from paddle_tpu.core import flags as core_flags
from paddle_tpu.profiler import counters, flight, health, metrics
from paddle_tpu.profiler.health import (SLO, HealthMonitor, Snapshot,
                                        Watchdog, Window)
from paddle_tpu.profiler.metrics import Histogram
from paddle_tpu.profiler.ops import OpsServer
from paddle_tpu.resilience import faultinject


@pytest.fixture(autouse=True)
def _health_flags(tmp_path):
    """Health ON with per-call ticks for these tests; flight dumps into
    the test's tmp dir; everything restored after."""
    core_flags.set_flags({"FLAGS_health": True,
                          "FLAGS_health_interval_s": 0.0})
    flight.configure(directory=str(tmp_path))
    yield
    core_flags.set_flags({"FLAGS_health": False,
                          "FLAGS_health_interval_s": 1.0})
    flight.clear()


_MODEL = None


def _model():
    global _MODEL
    if _MODEL is None:
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=4, max_seq_len=32,
                        use_flash_attention=False)
        paddle.seed(31)
        _MODEL = GPTForCausalLM(cfg)
        _MODEL.eval()
    return _MODEL


def _fired(before):
    """health.alerts.fired.* movement since ``before`` (a counter
    snapshot)."""
    return {k: v for k, v in counters.delta(before).items()
            if k.startswith("health.alerts.fired.")}


# -- window math -------------------------------------------------------------
class TestWindowMath:
    def test_delta_and_rate(self):
        w = Window(Snapshot(10.0, 0, {"a": 5, "b": 2}, {}),
                   Snapshot(14.0, 1, {"a": 9, "b": 2, "c": 7}, {}))
        assert w.delta("a") == 4
        assert w.delta("b") == 0
        assert w.delta("c") == 7          # born inside the window
        assert w.delta("missing") == 0
        assert w.seconds == pytest.approx(4.0)
        assert w.rate("a") == pytest.approx(1.0)

    def test_counter_reset_restarts_from_zero(self):
        # counters.reset() between snapshots: the window must report the
        # post-reset value, never a negative delta
        w = Window(Snapshot(0.0, 0, {"c": 100}, {}),
                   Snapshot(5.0, 1, {"c": 3}, {}))
        assert w.delta("c") == 3
        assert w.rate("c") == pytest.approx(0.6)

    def test_gauge_reads_window_end(self):
        w = Window(Snapshot(0.0, 0, {"g": 1.0}, {}),
                   Snapshot(1.0, 1, {"g": 7.5}, {}))
        assert w.gauge("g") == 7.5
        assert w.gauge("absent", default=-1) == -1

    def test_histogram_bucket_delta(self):
        h = Histogram("t", "ns")
        for v in (1e6, 2e6, 4e6):
            h.record(v)
        prev = h.copy()
        for v in (32e6, 64e6):
            h.record(v)
        d = h.delta(prev)
        assert d.count == 2
        assert d.sum == pytest.approx(96e6)
        # the window p95 sees ONLY the new (slow) samples
        assert d.percentile(95) > 30e6
        # lifetime p95 would have been dragged down by the old fast ones
        assert h.percentile(50) < 10e6

    def test_histogram_delta_reset_safe(self):
        prev = Histogram("t", "ns")
        for _ in range(10):
            prev.record(5e6)
        cur = Histogram("t", "ns")     # registry was reset: fresh hist
        cur.record(1e6)
        d = cur.delta(prev)            # prev is not a prefix of cur
        assert d.count == 1            # full current state, not negative
        assert d.sum == pytest.approx(1e6)

    def test_window_hist_delta_and_percentile(self):
        h = Histogram("w", "ns")
        h.record(1e6)
        s1 = Snapshot(0.0, 0, {}, {"w": h.copy()})
        for _ in range(20):
            h.record(40e6)
        s2 = Snapshot(1.0, 1, {}, {"w": h.copy()})
        w = Window(s1, s2)
        assert w.hist_delta("w").count == 20
        assert w.percentile("w", 95) > 20e6
        assert w.hist_delta("missing") is None
        assert w.percentile("missing", 95) is None

    def test_monitor_window_spans(self):
        mon = HealthMonitor(rules=[])
        assert mon.window(5.0) is None           # <2 snapshots
        for t in range(8):
            mon.tick(now=float(t))
        w = mon.window(5.0)
        assert w.end.ts == 7.0
        assert w.seconds >= 5.0
        # wider than the ring: degrade to the widest available span
        w = mon.window(1000.0)
        assert w.start.ts == 0.0


# -- SLO burn-rate lifecycle -------------------------------------------------
def _lat_slo(name="lat_burn", target=10e6, windows=((5.0, 1.0),
                                                    (30.0, 1.0))):
    return SLO(name, ("hist_p95", "test.health.lat_ns"), target,
               windows=windows)


class TestBurnRate:
    def test_fires_then_resolves_across_synthetic_windows(self):
        h = metrics.get_histogram("test.health.lat_ns", "ns")
        mon = HealthMonitor(rules=[_lat_slo()])
        before = counters.snapshot()
        mon.tick(now=0.0)
        for _ in range(20):
            h.record(50e6)             # 5x the 10ms objective
        mon.tick(now=1.0)
        assert [a.name for a in mon.firing()] == ["lat_burn"]
        assert _fired(before) == {"health.alerts.fired.lat_burn": 1}
        assert mon.admission_level() == "critical"
        # healthy traffic; the slow samples age out of the fast window
        t = 1.0
        for _ in range(12):
            t += 1.0
            for _ in range(30):
                h.record(1e6)
            mon.tick(now=t)
        assert mon.firing() == []
        assert mon.admission_level() == "ok"
        d = counters.delta(before)
        assert d.get("health.alerts.resolved.lat_burn") == 1
        assert d.get("health.alerts.fired.lat_burn") == 1   # no refire

    def test_needs_every_window_burning(self):
        # slow burn only in the fast window -> once the ring spans the
        # slow window, the alert must NOT fire on a short blip
        h = metrics.get_histogram("test.health.blip_ns", "ns")
        slo = SLO("blip_burn", ("hist_p95", "test.health.blip_ns"), 10e6,
                  windows=((2.0, 1.0), (30.0, 4.0)))
        mon = HealthMonitor(rules=[slo])
        t = 0.0
        for _ in range(35):            # ring spans > 30s of clean history
            t += 1.0
            for _ in range(5):
                h.record(1e6)
            mon.tick(now=t)
        for _ in range(5):
            h.record(30e6)             # blip: burn 3 in the fast window
        mon.tick(now=t + 1.0)
        st = [s for s in mon.slo_status() if s["name"] == "blip_burn"][0]
        assert st["windows"][0]["burning"] is True
        assert st["windows"][1]["burning"] is False
        assert mon.firing() == []

    def test_abstains_below_min_count(self):
        h = metrics.get_histogram("test.health.sparse_ns", "ns")
        slo = SLO("sparse_burn", ("hist_p95", "test.health.sparse_ns"),
                  1e6, min_count=8)
        mon = HealthMonitor(rules=[slo])
        mon.tick(now=0.0)
        for _ in range(3):             # violating, but too few samples
            h.record(100e6)
        mon.tick(now=1.0)
        assert mon.firing() == []
        st = mon.slo_status()[0]
        assert st["windows"][0]["value"] is None

    def test_ratio_signal(self):
        slo = SLO("err_ratio", ("ratio", "test.health.errs",
                                "test.health.reqs"), 0.01,
                  windows=((5.0, 1.0),))
        mon = HealthMonitor(rules=[slo])
        mon.tick(now=0.0)
        counters.inc("test.health.reqs", 100)
        counters.inc("test.health.errs", 7)
        mon.tick(now=1.0)
        assert [a.name for a in mon.firing()] == ["err_ratio"]
        st = mon.slo_status()[0]
        assert st["windows"][0]["value"] == pytest.approx(0.07)


# -- watchdogs ---------------------------------------------------------------
class TestWatchdogs:
    def _mon_with(self, wd, **kw):
        return HealthMonitor(rules=[wd], **kw)

    def test_retrace_storm(self):
        wd = [w for w in health.default_watchdogs()
              if w.name == "retrace_storm"][0]
        mon = self._mon_with(wd)
        mon.tick(now=0.0)
        mon.tick(now=1.0)
        assert mon.firing() == []                    # clean: no retrace
        counters.inc("serving.retraces")
        mon.tick(now=2.0)
        assert [a.name for a in mon.firing()] == ["retrace_storm"]

    def test_kv_conservation(self):
        from paddle_tpu.serving.kvcache import BlockPool
        wd = [w for w in health.default_watchdogs()
              if w.name == "kv_conservation"][0]
        pool = BlockPool(n_blocks=8, block_size=4)
        holder = type("Eng", (), {})()
        holder.pool = pool
        mon = self._mon_with(wd).attach(holder)
        mon.tick(now=0.0)
        b = pool.alloc()
        mon.tick(now=1.0)
        assert mon.firing() == []                    # clean accounting
        pool._free.append(b)        # corrupt: block free AND referenced
        mon.tick(now=2.0)
        firing = mon.firing()
        assert [a.name for a in firing] == ["kv_conservation"]
        assert firing[0].severity == "critical"
        assert firing[0].detail["free_with_refs"] == 1

    def test_kv_backpressure(self):
        wd = [w for w in health.default_watchdogs()
              if w.name == "kv_backpressure"][0]
        mon = self._mon_with(wd)
        mon.tick(now=0.0)
        mon.tick(now=1.0)
        assert mon.firing() == []
        counters.inc("serving.kv.pool_exhausted")
        mon.tick(now=2.0)
        assert [a.name for a in mon.firing()] == ["kv_backpressure"]

    def test_goodput_accounted(self):
        wd = [w for w in health.default_watchdogs()
              if w.name == "goodput_accounted"][0]
        mon = self._mon_with(wd)
        counters.set_gauge("goodput.wall_ns", 0)     # no ledger report yet
        counters.set_gauge("goodput.accounted", 0.5)
        mon.tick(now=0.0)
        mon.tick(now=1.0)
        assert mon.firing() == []                    # abstain: no wall
        counters.set_gauge("goodput.wall_ns", 1e9)
        counters.set_gauge("goodput.accounted", 0.999)
        mon.tick(now=2.0)
        assert mon.firing() == []                    # healthy ledger
        counters.set_gauge("goodput.accounted", 0.5)
        mon.tick(now=3.0)
        assert [a.name for a in mon.firing()] == ["goodput_accounted"]
        counters.set_gauge("goodput.wall_ns", 0)

    def test_spec_acceptance_collapse(self):
        wd = [w for w in health.default_watchdogs()
              if w.name == "spec_acceptance"][0]
        mon = self._mon_with(wd)
        counters.set_gauge("serving.spec.acceptance", 0.01)
        mon.tick(now=0.0)
        mon.tick(now=1.0)
        assert mon.firing() == []          # collapse but no draft volume
        counters.inc("serving.spec.drafted", 32)
        mon.tick(now=2.0)
        assert [a.name for a in mon.firing()] == ["spec_acceptance"]
        counters.set_gauge("serving.spec.acceptance", 0.8)
        counters.inc("serving.spec.drafted", 32)
        mon.tick(now=3.0)
        assert mon.firing() == []          # healthy draft: resolves

    def test_prefetch_stall(self):
        wd = [w for w in health.default_watchdogs()
              if w.name == "prefetch_stall"][0]
        mon = self._mon_with(wd)
        mon.tick(now=0.0)
        counters.inc("io.prefetch_stall_ns", 1e9)
        mon.tick(now=10.0)                 # 10% of the window: fine
        assert mon.firing() == []
        counters.inc("io.prefetch_stall_ns", 13e9)
        mon.tick(now=20.0)                 # 70% of the 20s window
        assert [a.name for a in mon.firing()] == ["prefetch_stall"]

    def test_broken_rule_never_kills_the_tick(self):
        def boom(w, m):
            raise RuntimeError("rule bug")
        mon = self._mon_with(Watchdog("broken_rule", boom))
        mon.tick(now=0.0)
        mon.tick(now=1.0)                  # must not raise
        assert mon.firing() == []
        assert mon.ticks == 2


# -- alert lifecycle ---------------------------------------------------------
class TestAlertLifecycle:
    def test_dedupe_single_fire_single_dump(self):
        state = [True]
        mon = HealthMonitor(rules=[
            Watchdog("dedupe_rule", lambda w, m: (state[0], {}))])
        before = counters.snapshot()
        mon.tick(now=0.0)
        for t in range(1, 5):
            mon.tick(now=float(t))         # keeps firing every tick
        d = counters.delta(before)
        assert d.get("health.alerts.fired.dedupe_rule") == 1
        assert d.get("flight.dumps.health_dedupe_rule") == 1
        alert = mon.firing()[0]
        assert alert.fired_count == 1
        assert alert.last > alert.since    # refreshed while deduped

    def test_refire_after_resolve_counts_and_dumps_again(self):
        state = [True]
        mon = HealthMonitor(rules=[
            Watchdog("flappy_rule", lambda w, m: (state[0], {}))])
        before = counters.snapshot()
        mon.tick(now=0.0)
        mon.tick(now=1.0)                  # fire #1
        state[0] = False
        mon.tick(now=2.0)                  # resolve
        assert mon.firing() == []
        state[0] = True
        mon.tick(now=3.0)                  # fire #2
        d = counters.delta(before)
        assert d.get("health.alerts.fired.flappy_rule") == 2
        assert d.get("health.alerts.resolved.flappy_rule") == 1
        assert d.get("flight.dumps.health_flappy_rule") == 2
        assert mon.firing()[0].fired_count == 2

    def test_admission_level_follows_severity(self):
        deg, crit = [False], [False]
        mon = HealthMonitor(rules=[
            Watchdog("soft_rule", lambda w, m: (deg[0], {})),
            Watchdog("hard_rule", lambda w, m: (crit[0], {}),
                     severity="critical")])
        mon.tick(now=0.0)
        mon.tick(now=1.0)
        assert mon.admission_level() == "ok"
        deg[0] = True
        mon.tick(now=2.0)
        assert mon.admission_level() == "degraded"
        assert counters.get("health.admission_level") == 1
        crit[0] = True
        mon.tick(now=3.0)
        assert mon.admission_level() == "critical"
        assert counters.get("health.admission_level") == 2
        deg[0] = crit[0] = False
        mon.tick(now=4.0)
        assert mon.admission_level() == "ok"
        assert counters.get("health.admission_level") == 0

    def test_dump_bundle_names_rule_and_window(self, tmp_path):
        mon = HealthMonitor(rules=[
            Watchdog("bundle_rule", lambda w, m: (True, {"x": 1}))])
        mon.tick(now=0.0)
        counters.inc("test.health.moved")
        mon.tick(now=1.0)
        path = flight.last_dump_path()
        assert path is not None
        b = flight.load(path)
        assert b["reason"] == "health_bundle_rule"
        assert b["context"]["rule"] == "bundle_rule"
        assert b["context"]["detail"] == {"x": 1}
        win = b["context"]["window"]
        assert win["seconds"] == pytest.approx(1.0)
        assert win["delta"].get("test.health.moved") == 1
        # the bundle also embeds the live alert set via the provider hook
        assert b["health"]["admission_level"] == "degraded"
        assert b["health"]["alerts"][0]["name"] == "bundle_rule"


# -- zero-overhead off -------------------------------------------------------
class TestOffMode:
    def test_off_ticks_move_nothing(self):
        core_flags.set_flags({"FLAGS_health": False})
        mon = HealthMonitor()
        before = counters.snapshot()
        for _ in range(10):
            assert mon.maybe_tick() is None
        assert counters.delta(before) == {}
        assert mon.ticks == 0
        assert len(mon._ring) == 0
        assert mon.summary() == {"enabled": False,
                                 "admission_level": "ok",
                                 "alerts": [], "ticks": 0}
        core_flags.set_flags({"FLAGS_health": True})

    def test_interval_gates_tick_cadence(self):
        mon = HealthMonitor(rules=[], interval_s=10.0)
        assert mon.maybe_tick(now=0.0) is not None
        assert mon.maybe_tick(now=5.0) is None       # too soon
        assert mon.maybe_tick(now=10.0) is not None


# -- chaos-driven firing on real serving stacks ------------------------------
class TestChaos:
    def test_slow_decode_fires_exactly_itl_burn(self, tmp_path):
        from paddle_tpu.serving.fleet import ServingFleet
        fl = ServingFleet(_model(), replicas=2, threaded=False,
                          max_slots=2, max_seq_len=32, min_bucket=4,
                          queue_size=16, heartbeat_timeout_s=30.0,
                          warm_buckets=(3, 4))
        try:
            before = counters.snapshot()
            chs = [fl.submit([1, 2, 3], max_new_tokens=6)
                   for _ in range(4)]
            fl.join(chs)
            assert _fired(before) == {}              # clean leg: silence
            chs = [fl.submit([1, 2, 3], max_new_tokens=8)
                   for _ in range(4)]
            with faultinject.fault_schedule(
                    f"slow_decode@{chs[0].rid}*8"):
                fl.join(chs)
            fired = _fired(before)
            assert fired == {"health.alerts.fired.itl_burn": 1}
            b = flight.load(flight.last_dump_path())
            assert b["reason"] == "health_itl_burn"
            assert b["context"]["rule"] == "itl_burn"
            assert b["context"]["window"]["seconds"] > 0
            # the recommendation reaches both stats surfaces
            assert fl.stats()["health"]["admission_level"] == "critical"
            rst = fl.router.stats()["health"]
            assert rst["admission_level"] == "critical"
            assert "itl_burn" in rst["alerts"]
        finally:
            fl.close()

    def test_kv_pool_exhausted_fires_exactly_kv_backpressure(self):
        from paddle_tpu.serving import LLMEngine
        eng = LLMEngine(_model(), kv_layout="paged", max_slots=3,
                        max_seq_len=32, min_bucket=4, block_size=4,
                        prefill_chunk=8)
        mon = HealthMonitor(
            rules=[w for w in health.default_watchdogs()
                   if w.name in ("kv_backpressure", "kv_conservation")],
            interval_s=0.0).attach(eng)
        # warm first (compiles happen BEFORE the first snapshot)
        h0 = eng.add_request([1, 2, 3], max_new_tokens=3, seed=0)
        while not h0.is_finished:
            eng.step()
        mon.maybe_tick()
        before = counters.snapshot()
        h1 = eng.add_request([4, 5, 6], max_new_tokens=3, seed=1)
        with faultinject.fault_schedule(f"kv_pool_exhausted@{h1.rid}"):
            n = 0
            while not h1.is_finished:
                eng.step()
                mon.maybe_tick()
                n += 1
                assert n < 300
        fired = _fired(before)
        assert fired == {"health.alerts.fired.kv_backpressure": 1}
        b = flight.load(flight.last_dump_path())
        assert b["reason"] == "health_kv_backpressure"
        assert b["context"]["rule"] == "kv_backpressure"
        win = b["context"]["window"]
        assert win["delta"].get("serving.kv.pool_exhausted", 0) >= 1


# -- ops endpoints + stats wiring --------------------------------------------
class TestOpsEndpoints:
    def _get(self, srv, path):
        body = urllib.request.urlopen(srv.url(path), timeout=10).read()
        return json.loads(body)

    def test_alerts_slo_signals_live(self):
        h = metrics.get_histogram("test.health.ops_ns", "ns")
        mon = HealthMonitor(rules=[
            SLO("ops_burn", ("hist_p95", "test.health.ops_ns"), 10e6,
                windows=((5.0, 1.0),))])
        mon.tick(now=0.0)
        for _ in range(10):
            h.record(1e6)
        counters.inc("test.health.ops_reqs", 5)
        mon.tick(now=1.0)
        with OpsServer(monitor=mon) as srv:
            alerts = self._get(srv, "/alerts")
            assert alerts["enabled"] is True
            assert alerts["admission_level"] == "ok"
            assert alerts["firing"] == []
            slo = self._get(srv, "/slo")
            assert slo["slos"][0]["name"] == "ops_burn"
            assert slo["slos"][0]["windows"][0]["burn"] is not None
            sig = self._get(srv, "/signals")
            assert sig["rates_per_s"].get("test.health.ops_reqs") == \
                pytest.approx(5.0)
            assert "test.health.ops_ns" in sig["p95"]

    def test_healthz_degrades_while_firing(self):
        mon = HealthMonitor(rules=[
            Watchdog("ops_rule", lambda w, m: (True, {}))])
        mon.tick(now=0.0)
        mon.tick(now=1.0)
        assert mon.firing()
        with OpsServer(monitor=mon) as srv:
            hz = self._get(srv, "/healthz")
            assert hz["status"] == "degraded"
            assert hz["health"]["alerts"] == ["ops_rule"]
            alerts = self._get(srv, "/alerts")
            assert alerts["admission_level"] == "degraded"
            assert alerts["firing"] == ["ops_rule"]
            assert alerts["alerts"][0]["state"] == "firing"

    def test_endpoints_404_without_monitor(self):
        with OpsServer() as srv:
            for ep in ("/alerts", "/slo", "/signals"):
                with pytest.raises(urllib.error.HTTPError):
                    urllib.request.urlopen(srv.url(ep), timeout=10)

    def test_router_stats_without_fleet_is_disabled_stub(self):
        from paddle_tpu.serving.router import Router
        st = Router().stats()
        assert st["health"]["enabled"] is False
        assert st["health"]["admission_level"] == "ok"


class TestAttach:
    def test_attach_chains_and_dedupes(self):
        mon = HealthMonitor(rules=[])
        obj = object()
        assert mon.attach(obj) is mon
        mon.attach(obj)
        mon.attach(None)
        assert mon._pools() == [obj]
