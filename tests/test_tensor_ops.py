"""Op parity tests vs numpy (reference: test/legacy_test/op_test.py OpTest —
check_output against numpy + check_grad numeric-vs-analytic)."""

import numpy as np
import pytest

import paddle_tpu as paddle


def np_t(x):
    return np.asarray(x.numpy())


class TestCreation:
    def test_basic(self):
        assert paddle.zeros([2, 3]).shape == [2, 3]
        assert paddle.ones([2], "int64").numpy().sum() == 2
        assert np.allclose(np_t(paddle.full([2, 2], 3.5)), 3.5)
        assert np_t(paddle.arange(5)).tolist() == [0, 1, 2, 3, 4]
        assert np.allclose(np_t(paddle.linspace(0, 1, 5)),
                           np.linspace(0, 1, 5))
        assert np.allclose(np_t(paddle.eye(3)), np.eye(3))

    def test_to_tensor(self):
        t = paddle.to_tensor([[1.0, 2.0]])
        assert t.dtype == np.float32
        assert t.shape == [1, 2]
        ti = paddle.to_tensor([1, 2, 3])
        assert "int" in str(ti.dtype)

    def test_like(self):
        x = paddle.randn([3, 4])
        assert paddle.zeros_like(x).shape == [3, 4]
        assert np.allclose(np_t(paddle.full_like(x, 2.0)), 2.0)


class TestMath:
    def test_elementwise(self):
        a = paddle.to_tensor([1.0, 2.0, 3.0])
        b = paddle.to_tensor([4.0, 5.0, 6.0])
        assert np.allclose(np_t(a + b), [5, 7, 9])
        assert np.allclose(np_t(a * b), [4, 10, 18])
        assert np.allclose(np_t(b / a), [4, 2.5, 2])
        assert np.allclose(np_t(a - b), [-3, -3, -3])
        assert np.allclose(np_t(a ** 2), [1, 4, 9])
        assert np.allclose(np_t(paddle.exp(a)), np.exp([1, 2, 3]), rtol=1e-6)
        assert np.allclose(np_t(paddle.log(a)), np.log([1, 2, 3]), rtol=1e-6)
        assert np.allclose(np_t(paddle.sqrt(a)), np.sqrt([1, 2, 3]),
                           rtol=1e-6)

    def test_scalar_broadcast(self):
        a = paddle.to_tensor([1.0, 2.0])
        assert np.allclose(np_t(2 * a), [2, 4])
        assert np.allclose(np_t(1 - a), [0, -1])
        assert np.allclose(np_t(6 / a), [6, 3])

    def test_reduce(self):
        x = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
        assert float(np_t(paddle.sum(x))) == 66
        assert np.allclose(np_t(paddle.sum(x, axis=0)), [12, 15, 18, 21])
        assert np.allclose(np_t(paddle.mean(x)), 5.5)
        assert float(np_t(paddle.max(x))) == 11
        assert float(np_t(paddle.min(x))) == 0
        assert np.allclose(np_t(paddle.prod(paddle.to_tensor([2.0, 3.0]))), 6)

    def test_matmul(self):
        a = paddle.randn([3, 4])
        b = paddle.randn([4, 5])
        c = paddle.matmul(a, b)
        assert np.allclose(np_t(c), np_t(a) @ np_t(b), atol=1e-5)
        ct = paddle.matmul(a, paddle.randn([5, 4]), transpose_y=True)
        assert ct.shape == [3, 5]

    def test_cumsum_clip(self):
        x = paddle.to_tensor([1.0, 2.0, 3.0])
        assert np.allclose(np_t(paddle.cumsum(x)), [1, 3, 6])
        assert np.allclose(np_t(paddle.clip(x, 1.5, 2.5)), [1.5, 2, 2.5])

    def test_einsum(self):
        a = paddle.randn([2, 3])
        b = paddle.randn([3, 4])
        out = paddle.einsum("ij,jk->ik", a, b)
        assert np.allclose(np_t(out), np_t(a) @ np_t(b), atol=1e-5)


class TestManipulation:
    def test_reshape_transpose(self):
        x = paddle.arange(24).astype("float32")
        y = paddle.reshape(x, [2, 3, 4])
        assert y.shape == [2, 3, 4]
        z = paddle.transpose(y, [2, 0, 1])
        assert z.shape == [4, 2, 3]
        assert paddle.flatten(y, 1).shape == [2, 12]

    def test_concat_split_stack(self):
        a = paddle.ones([2, 3])
        b = paddle.zeros([2, 3])
        c = paddle.concat([a, b], axis=0)
        assert c.shape == [4, 3]
        s = paddle.stack([a, b], axis=0)
        assert s.shape == [2, 2, 3]
        parts = paddle.split(c, 2, axis=0)
        assert len(parts) == 2 and parts[0].shape == [2, 3]
        parts = paddle.split(c, [1, 3], axis=0)
        assert parts[1].shape == [3, 3]

    def test_squeeze_unsqueeze(self):
        x = paddle.ones([1, 3, 1])
        assert paddle.squeeze(x).shape == [3]
        assert paddle.squeeze(x, 0).shape == [3, 1]
        assert paddle.unsqueeze(x, 0).shape == [1, 1, 3, 1]

    def test_gather_scatter(self):
        x = paddle.to_tensor(np.arange(10, dtype=np.float32))
        idx = paddle.to_tensor([1, 3, 5])
        assert np.allclose(np_t(paddle.gather(x, idx)), [1, 3, 5])
        upd = paddle.to_tensor([[10.0], [20.0]])
        base = paddle.zeros([4, 1])
        out = paddle.scatter(base, paddle.to_tensor([0, 2]), upd)
        assert np.allclose(np_t(out).reshape(-1), [10, 0, 20, 0])

    def test_indexing(self):
        x = paddle.to_tensor(np.arange(12, dtype=np.float32).reshape(3, 4))
        assert np.allclose(np_t(x[1]), [4, 5, 6, 7])
        assert np.allclose(np_t(x[:, 1]), [1, 5, 9])
        assert float(x[2, 3].numpy()) == 11
        x[0] = 0.0
        assert np.allclose(np_t(x)[0], 0)

    def test_where_masked(self):
        x = paddle.to_tensor([1.0, -2.0, 3.0])
        out = paddle.where(x > 0, x, paddle.zeros_like(x))
        assert np.allclose(np_t(out), [1, 0, 3])

    def test_tile_expand(self):
        x = paddle.ones([1, 3])
        assert paddle.tile(x, [2, 2]).shape == [2, 6]
        assert paddle.expand(x, [4, 3]).shape == [4, 3]


class TestSearchSort:
    def test_argmax_sort_topk(self):
        x = paddle.to_tensor([[3.0, 1.0, 2.0]])
        assert int(paddle.argmax(x, axis=1).numpy()[0]) == 0
        s = paddle.sort(x, axis=1)
        assert np.allclose(np_t(s), [[1, 2, 3]])
        v, i = paddle.topk(x, 2, axis=1)
        assert np.allclose(np_t(v), [[3, 2]])
        assert np_t(i).tolist() == [[0, 2]]

    def test_unique(self):
        x = paddle.to_tensor([3, 1, 2, 1, 3])
        u = paddle.unique(x)
        assert np_t(u).tolist() == [1, 2, 3]


class TestLinalg:
    def test_solve_inv(self):
        a_np = np.array([[2.0, 0.0], [0.0, 4.0]], np.float32)
        a = paddle.to_tensor(a_np)
        inv = paddle.linalg.inv(a)
        assert np.allclose(np_t(inv), np.linalg.inv(a_np), atol=1e-5)
        b = paddle.to_tensor([[2.0], [4.0]])
        x = paddle.linalg.solve(a, b)
        assert np.allclose(np_t(x), [[1], [1]], atol=1e-5)

    def test_norm_svd(self):
        x = paddle.to_tensor([[3.0, 4.0]])
        assert abs(float(paddle.linalg.norm(x).numpy()) - 5.0) < 1e-5
        u, s, vt = paddle.linalg.svd(paddle.randn([4, 3]))
        assert s.shape == [3]


class TestStat:
    def test_var_std_median(self):
        x = paddle.to_tensor([1.0, 2.0, 3.0, 4.0])
        assert abs(float(paddle.var(x).numpy())
                   - np.var([1, 2, 3, 4], ddof=1)) < 1e-6
        assert abs(float(paddle.median(x).numpy()) - 2.5) < 1e-6


class TestDtype:
    def test_cast(self):
        x = paddle.ones([2], "float32")
        y = x.astype("int32")
        assert y.dtype == np.int32
        z = x.astype(paddle.bfloat16)
        assert "bfloat16" in str(z.dtype)


class TestTensorArray:
    """TensorArray API (reference: tensor/array.py — list-variable for
    loop constructs; python-list backed in the jit-tracing world)."""

    def test_write_read_length(self):
        arr = paddle.tensor.create_array("float32")
        arr = paddle.tensor.array_write(paddle.ones([2]), 0, arr)
        arr = paddle.tensor.array_write(paddle.zeros([2]), 1, arr)
        assert paddle.tensor.array_length(arr) == 2
        assert np.allclose(np.asarray(
            paddle.tensor.array_read(arr, 0).numpy()), 1.0)
        # overwrite
        arr = paddle.tensor.array_write(paddle.full([2], 7.0), 0, arr)
        assert np.allclose(np.asarray(
            paddle.tensor.array_read(arr, 0).numpy()), 7.0)

    def test_bounds(self):
        import pytest
        arr = paddle.tensor.create_array()
        with pytest.raises(IndexError):
            paddle.tensor.array_write(paddle.ones([1]), 3, arr)
        with pytest.raises(IndexError):
            paddle.tensor.array_read(arr, 0)
