"""Test config: 8 virtual CPU devices (SURVEY §4 — the XPU op-test harness
pattern: same suite runs on a simulated multi-device backend)."""

import os

os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")

import jax  # noqa: E402

# the axon sitecustomize pins jax_platforms=axon; override for tests
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu as paddle
    paddle.seed(2024)
    yield


@pytest.fixture(autouse=True, scope="module")
def _reclaim_executables():
    """Every XLA:CPU executable mmaps JIT code pages; a full
    single-process run accumulates mappings toward the kernel's
    vm.max_map_count ceiling (65530 default) and segfaults inside
    backend_compile once mmap fails.  Modules don't share compiled
    programs (each builds fresh model/closure objects), so dropping the
    compile caches at module boundaries reclaims the pages without
    forcing recompiles."""
    yield
    import gc
    jax.clear_caches()
    gc.collect()
