"""Test config: 8 virtual CPU devices (SURVEY §4 — the XPU op-test harness
pattern: same suite runs on a simulated multi-device backend)."""

import os

os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")

import jax  # noqa: E402

# the axon sitecustomize pins jax_platforms=axon; override for tests
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu as paddle
    paddle.seed(2024)
    yield
