"""Pallas kernel tests (interpret mode on CPU; reference pattern:
test/legacy_test/test_flash_attention.py comparing against naive math)."""

import numpy as np
import pytest

import paddle_tpu as paddle


def naive_attention(q, k, v, causal=False):
    d = q.shape[-1]
    logits = np.einsum("bshd,bthd->bhst", q, k) / np.sqrt(d)
    if causal:
        s, t = logits.shape[-2], logits.shape[-1]
        mask = np.tril(np.ones((s, t), bool))
        logits = np.where(mask, logits, -1e30)
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return np.einsum("bhst,bthd->bshd", p, v)


@pytest.fixture()
def interpret_mode():
    from paddle_tpu.kernels import flash_attention as fa
    from paddle_tpu.kernels import rms_norm as rn
    fa._INTERPRET[0] = True
    rn._INTERPRET[0] = True
    yield
    fa._INTERPRET[0] = False
    rn._INTERPRET[0] = False


class TestFlashAttentionKernel:
    @pytest.mark.parametrize("causal", [False, True])
    def test_forward_matches_naive(self, interpret_mode, causal):
        import jax.numpy as jnp
        from paddle_tpu.kernels.flash_attention import flash_attention_fwd
        rng = np.random.RandomState(0)
        q = rng.randn(1, 256, 2, 64).astype(np.float32)
        k = rng.randn(1, 256, 2, 64).astype(np.float32)
        v = rng.randn(1, 256, 2, 64).astype(np.float32)
        out = np.asarray(flash_attention_fwd(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=causal))
        ref = naive_attention(q, k, v, causal)
        assert np.allclose(out, ref, atol=2e-3), np.abs(out - ref).max()

    @pytest.mark.parametrize("causal", [False, True])
    def test_backward_matches_jax_grad(self, interpret_mode, causal):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.kernels.flash_attention import (
            flash_attention_fwd, reference_attention)
        rng = np.random.RandomState(1)
        q = jnp.asarray(rng.randn(1, 128, 1, 64).astype(np.float32))
        k = jnp.asarray(rng.randn(1, 128, 1, 64).astype(np.float32))
        v = jnp.asarray(rng.randn(1, 128, 1, 64).astype(np.float32))

        def loss_kernel(q, k, v):
            return jnp.sum(flash_attention_fwd(q, k, v, causal=causal) ** 2)

        def loss_ref(q, k, v):
            return jnp.sum(reference_attention(q, k, v, causal=causal) ** 2)

        gk = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(gk, gr, "qkv"):
            assert np.allclose(np.asarray(a), np.asarray(b), atol=5e-2), (
                name, np.abs(np.asarray(a) - np.asarray(b)).max())


class TestFlashAttentionTPULowering:
    """Round-1 regression: the kernel passed interpret mode but failed Mosaic
    lowering on real TPU (illegal LSE BlockSpec).  Cross-lower for the TPU
    target from the CPU host via jax.export so CI catches lowering errors."""

    def test_kernel_lowers_for_tpu(self):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.kernels.flash_attention import _flash_attention_bhsd

        b, h, s, d = 2, 12, 1024, 64
        q = jax.ShapeDtypeStruct((b, h, s, d), jnp.bfloat16)

        def fwd_bwd(q, k, v):
            out, vjp = jax.vjp(
                lambda q, k, v: _flash_attention_bhsd(q, k, v, True, 0.125),
                q, k, v)
            return out, vjp(out)

        exported = jax.export.export(jax.jit(fwd_bwd), platforms=["tpu"])(
            q, q, q)
        assert "tpu" in exported.platforms


class TestRMSNormKernel:
    def test_matches_reference(self, interpret_mode):
        import jax.numpy as jnp
        from paddle_tpu.kernels.rms_norm import rms_norm, rms_norm_reference
        x = jnp.asarray(np.random.randn(8, 128).astype(np.float32))
        w = jnp.asarray(np.random.randn(128).astype(np.float32))
        out = rms_norm(x, w)
        ref = rms_norm_reference(x, w)
        assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


class TestRope:
    def test_rope_properties(self):
        import jax.numpy as jnp
        from paddle_tpu.kernels.rope import apply_rope
        x = jnp.asarray(np.random.randn(1, 16, 2, 32).astype(np.float32))
        out = apply_rope(x)
        # norm-preserving per pair
        assert np.allclose(np.linalg.norm(np.asarray(out), axis=-1),
                           np.linalg.norm(np.asarray(x), axis=-1), atol=1e-4)
        # position 0 unchanged
        assert np.allclose(np.asarray(out)[:, 0], np.asarray(x)[:, 0],
                           atol=1e-6)
