"""Elastic training: kill-a-worker restart + heartbeat watchdog.

Reference: fleet/elastic/manager.py:124 (relaunch on fault) and
comm_task_manager.cc:171-217 (hang watchdog)."""

import os
import sys
import textwrap

import numpy as np
import pytest


TRAIN_SCRIPT = textwrap.dedent("""
    import json
    import os
    import sys

    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    import jax
    jax.config.update("jax_platforms", "cpu")

    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu.distributed.elastic import heartbeat

    out_dir = sys.argv[1]
    mode = sys.argv[2]              # 'crash' | 'hang' | 'clean'
    restart = int(os.environ.get("PADDLE_RESTART_COUNT", "0"))
    ckpt = os.path.join(out_dir, "ckpt")

    paddle.seed(4)
    net = nn.Linear(4, 4)
    opt = paddle.optimizer.SGD(0.1, parameters=net.parameters())
    rng = np.random.RandomState(0)
    xs = [paddle.to_tensor(rng.randn(8, 4).astype(np.float32))
          for _ in range(6)]

    start = 0
    if restart > 0 and os.path.isdir(ckpt):
        state = {"w": net.weight, "b": net.bias}
        paddle.distributed.load_state_dict(state, ckpt)
        with open(os.path.join(out_dir, "resume_step")) as f:
            start = int(f.read())

    losses = []
    for step in range(start, 6):
        loss = ((net(xs[step]) ** 2).mean())
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
        heartbeat()
        paddle.distributed.save_state_dict(
            {"w": net.weight, "b": net.bias}, ckpt)
        with open(os.path.join(out_dir, "resume_step"), "w") as f:
            f.write(str(step + 1))
        with open(os.path.join(out_dir, f"losses.r{restart}"), "w") as f:
            json.dump(losses, f)
        if step == 2 and restart == 0:
            if mode == "crash":
                os._exit(17)        # simulated worker death mid-training
            if mode == "hang":
                import time
                time.sleep(3600)    # wedged step: heartbeat goes stale
""")


def _run_elastic(tmp_path, mode, extra_args=()):
    from paddle_tpu.distributed.elastic import ElasticAgent
    script = tmp_path / "train.py"
    script.write_text(TRAIN_SCRIPT)
    out = tmp_path / "out"
    out.mkdir()
    agent = ElasticAgent(
        [sys.executable, str(script), str(out), mode],
        nproc=1, log_dir=str(tmp_path / "log"), max_restarts=2,
        heartbeat_timeout=(8 if mode == "hang" else None),
        env={**os.environ,
             "PYTHONPATH": os.path.dirname(os.path.dirname(
                 os.path.abspath(__file__)))})
    rc = agent.run()
    return rc, agent, out


def _expected_losses(tmp_path):
    """Uninterrupted single-process run of the same script."""
    import json
    import subprocess
    script = tmp_path / "train_ref.py"
    script.write_text(TRAIN_SCRIPT)
    out = tmp_path / "ref_out"
    out.mkdir()
    subprocess.run(
        [sys.executable, str(script), str(out), "clean"],
        check=True, timeout=240,
        env={**os.environ, "PYTHONPATH": os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))})
    with open(out / "losses.r0") as f:
        return json.load(f)


@pytest.mark.slow
class TestElastic:
    def test_crash_restart_resumes_and_matches(self, tmp_path):
        """A worker dying mid-run is relaunched; it resumes from the
        distributed checkpoint and the post-resume losses MATCH an
        uninterrupted run step-for-step."""
        import json
        rc, agent, out = _run_elastic(tmp_path, "crash")
        assert rc == 0, agent.events
        kinds = [k for _, k, _ in agent.events]
        assert "failure" in kinds and kinds[-1] == "done", agent.events
        with open(out / "losses.r0") as f:
            first = json.load(f)
        with open(out / "losses.r1") as f:
            resumed = json.load(f)
        ref = _expected_losses(tmp_path)
        # run 0 covered steps 0..2, the resumed run steps 3..5
        assert np.allclose(first, ref[:3], rtol=1e-6), (first, ref)
        assert np.allclose(resumed, ref[3:], rtol=1e-6), (resumed, ref)

    def test_hang_watchdog_restarts(self, tmp_path):
        """A wedged step (stale heartbeat) trips the watchdog; the relaunch
        completes the run."""
        rc, agent, out = _run_elastic(tmp_path, "hang")
        assert rc == 0, agent.events
        details = [d for _, k, d in agent.events if k == "failure"]
        assert any("heartbeat stale" in d for d in details), agent.events
        assert (out / "losses.r1").exists()

    def test_giveup_after_max_restarts(self, tmp_path):
        """A persistently-failing script exhausts max_restarts and the
        agent reports failure instead of looping forever."""
        from paddle_tpu.distributed.elastic import ElasticAgent
        script = tmp_path / "bad.py"
        script.write_text("import sys; sys.exit(3)\n")
        agent = ElasticAgent([sys.executable, str(script)], nproc=1,
                             log_dir=str(tmp_path / "log"), max_restarts=2,
                             poll_interval=0.1)
        assert agent.run() == 1
        assert [k for _, k, _ in agent.events].count("failure") == 3


def _run_launcher(script, tmp_path):
    import subprocess
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    return subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node=1", "--log_dir", str(tmp_path / "log"),
         str(script)],
        capture_output=True, text=True, timeout=120, cwd=repo)


class TestLauncher:
    def test_fleetrun_single_host(self, tmp_path):
        """The fleetrun launcher runs a script end-to-end (reference:
        launch/main.py) and propagates the worker exit code."""
        script = tmp_path / "train.py"
        script.write_text(
            "import os\n"
            "assert os.environ['PADDLE_TRAINER_ID'] == '0'\n"
            "assert os.environ['PADDLE_TRAINERS_NUM'] == '1'\n"
            "print('WORKER OK')\n")
        r = _run_launcher(script, tmp_path)
        assert r.returncode == 0, (r.stdout, r.stderr)
        assert "WORKER OK" in r.stdout

    def test_fleetrun_propagates_failure(self, tmp_path):
        script = tmp_path / "bad.py"
        script.write_text("import sys; sys.exit(9)\n")
        r = _run_launcher(script, tmp_path)
        assert r.returncode != 0
