"""paddle.static Program/Executor tests.

Reference analogue: test/legacy_test/test_program.py, test_executor_*.py —
program capture under program_guard, feed/fetch execution, backward.
Here the program is a recorded kernel list replayed inside one jax.jit
(see paddle_tpu/static/__init__.py).
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import static


def _build_mlp_program():
    main = static.Program()
    startup = static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 4], "float32")
        lin = paddle.nn.Linear(4, 3)
        h = paddle.tanh(lin(x))
        loss = paddle.mean(h * h)
    return main, startup, x, lin, h, loss


class TestProgramCapture:
    def test_ops_recorded(self):
        main, _, x, lin, h, loss = _build_mlp_program()
        assert "linear" in main.ops
        assert "tanh" in main.ops
        assert "mean" in main.ops

    def test_recording_scoped_to_guard(self):
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [2, 2])
            y = paddle.exp(x)
        n = len(main.ops)
        # outside the guard nothing is appended
        paddle.exp(paddle.to_tensor(np.ones((2, 2), np.float32)))
        assert len(main.ops) == n

    def test_ir_dump_shows_feeds_params_spmd(self):
        main, *_ = _build_mlp_program()
        s = str(main)
        assert "feed['x']" in s
        assert "param shape=(4, 3)" in s
        assert "[spmd: elementwise]" in s  # tanh
        assert "[spmd: reduction]" in s    # mean

    def test_clone(self):
        main, *_ = _build_mlp_program()
        c = main.clone(for_test=True)
        assert c.ops == main.ops


class TestExecutor:
    def test_run_matches_eager(self):
        main, startup, x, lin, h, loss = _build_mlp_program()
        exe = static.Executor()
        assert exe.run(startup) == []
        arr = np.random.RandomState(0).rand(5, 4).astype(np.float32)
        got_h, got_loss = exe.run(main, feed={"x": arr},
                                  fetch_list=[h, loss])
        ref = paddle.tanh(lin(paddle.to_tensor(arr)))
        np.testing.assert_allclose(got_h, ref.numpy(), atol=1e-6)
        np.testing.assert_allclose(got_loss,
                                   float((ref * ref).mean().numpy()),
                                   rtol=1e-6)

    def test_feed_shape_polymorphic(self):
        """data([None, 4]) runs at any batch (each shape compiles once)."""
        main, _, x, lin, h, _ = _build_mlp_program()
        exe = static.Executor()
        for b in (1, 3, 8):
            out = exe.run(main, feed={"x": np.ones((b, 4), np.float32)},
                          fetch_list=[h])[0]
            assert out.shape == (b, 3)

    def test_param_updates_are_live(self):
        """Externals resolve at run time: updating the layer's weights
        changes the program's output without re-capture."""
        main, _, x, lin, h, _ = _build_mlp_program()
        exe = static.Executor()
        arr = np.ones((2, 4), np.float32)
        before = exe.run(main, feed={"x": arr}, fetch_list=[h])[0]
        with paddle.no_grad():
            w = lin.parameters()[0]
            w.set_value(w * 0.0)
        after = exe.run(main, feed={"x": arr}, fetch_list=[h])[0]
        assert not np.allclose(before, after)
        np.testing.assert_allclose(after, 0.0, atol=1e-6)

    def test_unknown_feed_rejected(self):
        main, *_ = _build_mlp_program()
        with pytest.raises(KeyError):
            static.Executor().run(main, feed={"bogus": np.ones(1)},
                                  fetch_list=[None])

    def test_comparison_ops_replay(self):
        """logic ops (no-tape path) must be recorded, not baked to the
        placeholder's value."""
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [4])
            m = paddle.greater_than(x, paddle.to_tensor(
                np.zeros(4, np.float32)))
        exe = static.Executor()
        out = exe.run(main, feed={"x": np.array([-1, 1, -2, 2],
                                                np.float32)},
                      fetch_list=[m])[0]
        np.testing.assert_array_equal(out, [False, True, False, True])


class TestBackward:
    def test_gradients_match_eager(self):
        main, _, x, lin, h, loss = _build_mlp_program()
        w, b = lin.parameters()
        gw, = static.gradients(loss, [w])
        exe = static.Executor()
        arr = np.random.RandomState(1).rand(6, 4).astype(np.float32)
        got = exe.run(main, feed={"x": arr}, fetch_list=[gw])[0]

        # eager reference
        w.stop_gradient = False
        ref_loss = paddle.mean(paddle.tanh(lin(paddle.to_tensor(arr))) ** 2)
        ref_loss.backward()
        np.testing.assert_allclose(got, w.grad.numpy(), atol=1e-5)

    def test_append_backward_lists_params(self):
        main, _, x, lin, h, loss = _build_mlp_program()
        pairs = static.append_backward(loss)
        assert len(pairs) == 2  # weight + bias
        exe = static.Executor()
        arr = np.ones((2, 4), np.float32)
        grads = exe.run(main, feed={"x": arr},
                        fetch_list=[g for _, g in pairs])
        assert grads[0].shape == tuple(lin.parameters()[0].shape)
        assert grads[1].shape == tuple(lin.parameters()[1].shape)


class TestStaticNN:
    def test_fc(self):
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [None, 8])
            y = static.nn.fc(x, 4, activation="relu")
        out = static.Executor().run(
            main, feed={"x": np.random.rand(2, 8).astype(np.float32)},
            fetch_list=[y])[0]
        assert out.shape == (2, 4)
        assert (out >= 0).all()


def test_save_load_params(tmp_path):
    main, _, x, lin, h, _ = _build_mlp_program()
    exe = static.Executor()
    arr = np.ones((2, 4), np.float32)
    before = exe.run(main, feed={"x": arr}, fetch_list=[h])[0]
    p = str(tmp_path / "prog")
    static.save(main, p)
    with paddle.no_grad():
        w = lin.parameters()[0]
        w.set_value(w + 1.0)
    changed = exe.run(main, feed={"x": arr}, fetch_list=[h])[0]
    assert not np.allclose(before, changed)
    static.load(main, p)
    restored = exe.run(main, feed={"x": arr}, fetch_list=[h])[0]
    np.testing.assert_allclose(restored, before, atol=1e-6)


class TestReviewedEdges:
    def test_gradient_wrt_intermediate(self):
        """d(loss)/d(h) for an intermediate h: downstream-only sensitivity
        (the producer's value is overridden, not recomputed)."""
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [None, 3])
            h = paddle.tanh(x)
            loss = paddle.sum(h * h)
        gh, = static.gradients(loss, [h])
        arr = np.random.RandomState(3).rand(2, 3).astype(np.float32)
        got = static.Executor().run(main, feed={"x": arr},
                                    fetch_list=[gh])[0]
        np.testing.assert_allclose(got, 2.0 * np.tanh(arr), atol=1e-6)

    def test_gradients_sum_over_multiple_targets(self):
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [4])
            a = paddle.sum(x * x)
            b = paddle.sum(3.0 * x)
        gx, = static.gradients([a, b], [x])
        arr = np.array([1.0, 2.0, 3.0, 4.0], np.float32)
        got = static.Executor().run(main, feed={"x": arr},
                                    fetch_list=[gx])[0]
        np.testing.assert_allclose(got, 2 * arr + 3.0, atol=1e-6)

    def test_target_gradients_rejected(self):
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [2])
            y = paddle.sum(x)
        with pytest.raises(NotImplementedError):
            static.gradients(y, [x], target_gradients=[y])

    def test_clone_variables_fetchable(self):
        main, _, x, lin, h, loss = _build_mlp_program()
        test_prog = main.clone(for_test=True)
        out = static.Executor().run(
            test_prog, feed={"x": np.ones((2, 4), np.float32)},
            fetch_list=[h])[0]
        assert out.shape == (2, 3)

    def test_missing_feed_named_in_error(self):
        main = static.Program()
        with static.program_guard(main):
            a = static.data("a", [2])
            b = static.data("b", [2])
            y = a + b
        with pytest.raises(KeyError, match="b"):
            static.Executor().run(main, feed={"a": np.ones(2, np.float32)},
                                  fetch_list=[y])

    def test_fc_num_flatten_dims(self):
        main = static.Program()
        with static.program_guard(main):
            x = static.data("x", [None, 3, 4])
            y = static.nn.fc(x, 5, num_flatten_dims=1)
        out = static.Executor().run(
            main, feed={"x": np.ones((2, 3, 4), np.float32)},
            fetch_list=[y])[0]
        assert out.shape == (2, 5)
