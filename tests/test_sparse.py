"""paddle.sparse: COO/CSR creation, unary/binary ops, SDDMM
(reference: python/paddle/sparse/ — creation.py, unary.py, binary.py)."""

import numpy as np
import pytest

import paddle_tpu as paddle

sp = paddle.sparse


def _dense():
    return paddle.to_tensor(np.asarray(
        [[0, 2.0, 0, 1.0], [3.0, 0, 0, 0], [0, 0, -4.0, 0]], np.float32))


class TestCreation:
    def test_coo_roundtrip(self):
        x = _dense()
        c = sp.to_sparse_coo(x)
        assert sp.nnz(c) == 4
        assert np.allclose(np.asarray(c.to_dense().numpy()),
                           np.asarray(x.numpy()))

    def test_csr_roundtrip(self):
        x = _dense()
        c = sp.to_sparse_csr(x)
        assert np.allclose(np.asarray(c.to_dense().numpy()),
                           np.asarray(x.numpy()))
        assert list(np.asarray(c.crows().numpy())) == [0, 2, 3, 4]

    def test_sparse_coo_tensor_duplicates_sum(self):
        c = sp.sparse_coo_tensor(np.asarray([[0, 0], [1, 1]]),
                                 np.asarray([1.0, 2.0], np.float32),
                                 shape=(2, 2))
        assert float(np.asarray(c.to_dense().numpy())[0, 1]) == 3.0


class TestUnary:
    @pytest.mark.parametrize("name,ref", [
        ("sin", np.sin), ("tanh", np.tanh), ("square", np.square),
        ("abs", np.abs), ("neg", np.negative), ("expm1", np.expm1),
        ("relu", lambda v: np.maximum(v, 0))])
    def test_value_ops_preserve_pattern(self, name, ref):
        x = _dense()
        c = sp.to_sparse_coo(x)
        out = getattr(sp, name)(c)
        assert np.allclose(np.asarray(out.to_dense().numpy()),
                           ref(np.asarray(x.numpy())), atol=1e-6), name
        assert sp.nnz(out) == sp.nnz(c)  # same sparsity pattern

    def test_pow_and_cast(self):
        c = sp.to_sparse_coo(_dense())
        p = sp.pow(c, 2)
        assert np.allclose(np.asarray(p.to_dense().numpy()),
                           np.asarray(_dense().numpy()) ** 2)
        c2 = sp.cast(c, value_dtype="float64")
        assert c2 is not None


class TestBinary:
    def test_add_subtract(self):
        a = sp.to_sparse_coo(_dense())
        b = sp.to_sparse_coo(_dense())
        out = sp.add(a, b)
        assert np.allclose(np.asarray(out.to_dense().numpy()),
                           2 * np.asarray(_dense().numpy()))
        z = sp.subtract(a, b)
        assert np.allclose(np.asarray(z.to_dense().numpy()), 0)

    def test_matmul_and_mv(self):
        a = sp.to_sparse_coo(_dense())          # [3, 4]
        d = paddle.to_tensor(np.random.RandomState(0)
                             .randn(4, 2).astype(np.float32))
        out = sp.matmul(a, d)
        ref = np.asarray(_dense().numpy()) @ np.asarray(d.numpy())
        assert np.allclose(np.asarray(out.numpy()), ref, atol=1e-5)
        v = paddle.to_tensor(np.ones(4, np.float32))
        assert np.allclose(np.asarray(sp.mv(a, v).numpy()),
                           np.asarray(_dense().numpy()).sum(1), atol=1e-6)

    def test_masked_matmul_sddmm(self):
        rng = np.random.RandomState(1)
        a = paddle.to_tensor(rng.randn(3, 4).astype(np.float32))
        b = paddle.to_tensor(rng.randn(4, 3).astype(np.float32))
        mask = sp.to_sparse_coo(paddle.to_tensor(
            np.eye(3, dtype=np.float32)))
        out = sp.masked_matmul(a, b, mask)
        ref = (np.asarray(a.numpy()) @ np.asarray(b.numpy())) * np.eye(3)
        assert np.allclose(np.asarray(out.to_dense().numpy()), ref,
                           atol=1e-5)


class TestAutograd:
    def test_dense_path_keeps_gradients(self):
        """sparse.relu / sparse.add on dense tensors route through the
        dispatch (round-5 review regression: raw jnp calls dropped the
        autograd tape)."""
        x = paddle.to_tensor(np.asarray([[1.0, -2.0], [3.0, -4.0]],
                                        np.float32))
        x.stop_gradient = False
        out = sp.relu(x)
        assert not out.stop_gradient
        out.sum().backward()
        g = np.asarray(x.grad.numpy())
        assert np.allclose(g, (np.asarray(x.numpy()) > 0).astype(np.float32))

    def test_divide_mismatched_pattern_raises(self):
        a = sp.to_sparse_coo(_dense())
        b = sp.to_sparse_coo(paddle.to_tensor(
            np.asarray([[1.0, 0, 0, 0], [0, 0, 0, 0], [0, 0, 0, 0]],
                       np.float32)))
        with pytest.raises(ValueError, match="sparsity patterns"):
            sp.divide(a, b)

    def test_csr_transpose_preserves_format(self):
        c = sp.to_sparse_csr(_dense())
        t = sp.transpose(c, [1, 0])
        assert hasattr(t, "crows")  # still CSR
        assert np.allclose(np.asarray(t.to_dense().numpy()),
                           np.asarray(_dense().numpy()).T)


class TestSparseNN:
    def test_relu_layer(self):
        layer = sp.nn.ReLU()
        out = layer(_dense())
        assert float(np.asarray(out.numpy()).min()) >= 0
