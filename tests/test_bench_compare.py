"""scripts/bench_compare.py — the BENCH-trajectory perf-regression gate.

Pure-python unit coverage (no model, no engine): metric classification,
leg flattening, run extraction (including the legacy flagship schema),
longest-suffix tolerance overrides, best-prior-per-(leg, metric)
anchoring, and the CLI's 0 / 1 / 2 exit-status contract.
"""

import importlib.util
import json
import pathlib

import pytest


def _load():
    path = (pathlib.Path(__file__).resolve().parent.parent / "scripts"
            / "bench_compare.py")
    spec = importlib.util.spec_from_file_location("bench_compare", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


bc = _load()


# -- classification ----------------------------------------------------------
class TestClassify:
    @pytest.mark.parametrize("metric", [
        "tokens_per_sec", "mfu", "decode.tokens_per_sec",
        "prefix.hit_rate", "spec.acceptance", "vs_baseline",
        "capacity_ratio", "goodput.fraction",
    ])
    def test_throughput_like_must_not_drop(self, metric):
        assert bc.classify(metric) == "higher"

    @pytest.mark.parametrize("metric", [
        "ttft.p95_ms", "itl.p95_ms", "queue_wait.mean",
        "latency_ms", "step_time", "save_ms", "restore_s",
        "decode.p99", "migrate.p50_ms",
    ])
    def test_latency_like_must_not_rise(self, metric):
        assert bc.classify(metric) == "lower"

    @pytest.mark.parametrize("metric", [
        "count", "requests.count", "spread_frac", "n_params",
        "some_unknown_metric",
    ])
    def test_informational_metrics_are_not_gated(self, metric):
        assert bc.classify(metric) is None

    def test_skip_beats_direction_keywords(self):
        # the skip list is checked FIRST: a count of latency samples is
        # not itself a latency
        assert bc.classify("ttft.count") is None


# -- flattening / extraction -------------------------------------------------
class TestFlatten:
    def test_nested_dotted_paths(self):
        flat = bc._flatten({"ttft": {"p95_ms": 12.5, "count": 4},
                            "tokens_per_sec": 100})
        assert flat == {"ttft.p95_ms": 12.5, "ttft.count": 4.0,
                        "tokens_per_sec": 100.0}

    def test_bools_and_strings_are_skipped(self):
        flat = bc._flatten({"ok": True, "name": "gpt", "v": 2})
        assert flat == {"v": 2.0}


class TestExtract:
    def test_failed_run_is_skipped(self):
        assert bc.extract({"rc": 1, "parsed": {"legs": {
            "a": {"tokens_per_sec": 1}}}}) is None

    def test_unparsed_run_is_skipped(self):
        assert bc.extract({"rc": 0, "parsed": None}) is None
        assert bc.extract({"rc": 0}) is None

    def test_legs_schema(self):
        legs = bc.extract({"rc": 0, "parsed": {"legs": {
            "serve": {"ttft": {"p95_ms": 9.0}},
            "train": {"tokens_per_sec": 50.0},
            "bogus": 3}}})
        assert legs == {"serve": {"ttft.p95_ms": 9.0},
                        "train": {"tokens_per_sec": 50.0}}

    def test_legacy_flagship_train_metric(self):
        # "gpt125m_train_tokens_per_sec_per_chip" → leg gpt125m with
        # tokens_per_sec, vs_baseline re-labelled mfu
        legs = bc.extract({"rc": None, "parsed": {
            "metric": "gpt125m_train_tokens_per_sec_per_chip",
            "value": 123.0, "vs_baseline": 0.4}})
        assert legs == {"gpt125m": {"tokens_per_sec": 123.0,
                                    "mfu": 0.4}}

    def test_legacy_nonmatching_metric_lands_on_flagship_leg(self):
        legs = bc.extract({"rc": 0, "parsed": {
            "metric": "serve_goodput", "value": 7.0,
            "vs_baseline": 1.1}})
        assert legs == {"_flagship": {"tokens_per_sec": 7.0,
                                      "vs_baseline": 1.1}}

    def test_empty_parse_is_none(self):
        assert bc.extract({"rc": 0, "parsed": {"metric": "x"}}) is None


# -- tolerance overrides -----------------------------------------------------
class TestTolFor:
    def test_default_when_no_override_matches(self):
        assert bc.tol_for("ttft.p95_ms", 0.1, {"mfu": 0.05}) == 0.1

    def test_exact_and_suffix_match(self):
        ov = {"p95_ms": 0.25, "mfu": 0.05}
        assert bc.tol_for("ttft.p95_ms", 0.1, ov) == 0.25
        assert bc.tol_for("mfu", 0.1, ov) == 0.05

    def test_longest_suffix_wins(self):
        ov = {"p95_ms": 0.5, "ttft.p95_ms": 0.2}
        assert bc.tol_for("serve.ttft.p95_ms", 0.1, ov) == 0.2


# -- comparison --------------------------------------------------------------
def _run(path, **legs):
    return {"path": path, "n": None,
            "legs": {leg: dict(m) for leg, m in legs.items()}}


class TestCompare:
    def test_anchors_on_best_prior_not_last(self):
        """A slow decay across runs cannot hide: the candidate is held
        to the trajectory's best (max for throughput, min for latency),
        not the immediately previous run."""
        history = [
            _run("r1", serve={"tokens_per_sec": 100.0, "ttft.p95_ms": 5.0}),
            _run("r2", serve={"tokens_per_sec": 80.0, "ttft.p95_ms": 9.0}),
        ]
        cand = _run("r3", serve={"tokens_per_sec": 85.0,
                                 "ttft.p95_ms": 6.0})
        regs, checks = bc.compare(history, cand, 0.1, {})
        by = {(c["leg"], c["metric"]): c for c in checks}
        assert by[("serve", "tokens_per_sec")]["best_prior"] == 100.0
        assert by[("serve", "ttft.p95_ms")]["best_prior"] == 5.0
        # 85 < 100*0.9 and 6 > 5*1.1: both regressed vs the BEST even
        # though both beat r2
        assert {(r["leg"], r["metric"]) for r in regs} == \
            {("serve", "tokens_per_sec"), ("serve", "ttft.p95_ms")}

    def test_within_tolerance_is_clean(self):
        history = [_run("r1", serve={"tokens_per_sec": 100.0})]
        cand = _run("r2", serve={"tokens_per_sec": 91.0})
        regs, checks = bc.compare(history, cand, 0.1, {})
        assert regs == [] and len(checks) == 1

    def test_tol_for_override_applies(self):
        history = [_run("r1", serve={"ttft.p95_ms": 10.0})]
        cand = _run("r2", serve={"ttft.p95_ms": 12.0})
        regs, _ = bc.compare(history, cand, 0.1, {})
        assert len(regs) == 1
        regs, _ = bc.compare(history, cand, 0.1, {"p95_ms": 0.25})
        assert regs == []

    def test_new_metric_without_prior_is_not_checked(self):
        history = [_run("r1", serve={"tokens_per_sec": 100.0})]
        cand = _run("r2", serve={"tokens_per_sec": 100.0},
                    disagg={"itl.p95_ms": 3.0})
        regs, checks = bc.compare(history, cand, 0.1, {})
        assert regs == []
        assert [(c["leg"], c["metric"]) for c in checks] == \
            [("serve", "tokens_per_sec")]

    def test_informational_metrics_never_regress(self):
        history = [_run("r1", serve={"requests.count": 100.0})]
        cand = _run("r2", serve={"requests.count": 1.0})
        regs, checks = bc.compare(history, cand, 0.1, {})
        assert regs == [] and checks == []


# -- CLI exit-status contract ------------------------------------------------
def _write(tmp_path, name, tps):
    d = {"rc": 0, "parsed": {"legs": {"serve": {"tokens_per_sec": tps}}}}
    p = tmp_path / name
    p.write_text(json.dumps(d))
    return p


class TestMain:
    def test_rc0_clean(self, tmp_path, capsys):
        _write(tmp_path, "BENCH_r01.json", 100.0)
        _write(tmp_path, "BENCH_r02.json", 105.0)
        rc = bc.main(["--glob", str(tmp_path / "BENCH_r0*.json")])
        assert rc == 0
        assert "0 regression(s)" in capsys.readouterr().out

    def test_rc1_regression(self, tmp_path, capsys):
        _write(tmp_path, "BENCH_r01.json", 100.0)
        _write(tmp_path, "BENCH_r02.json", 50.0)
        rc = bc.main(["--glob", str(tmp_path / "BENCH_r0*.json")])
        assert rc == 1
        assert "REGRESSED" in capsys.readouterr().out

    def test_rc1_json_report(self, tmp_path, capsys):
        _write(tmp_path, "BENCH_r01.json", 100.0)
        _write(tmp_path, "BENCH_r02.json", 50.0)
        rc = bc.main(["--glob", str(tmp_path / "BENCH_r0*.json"),
                      "--json"])
        assert rc == 1
        rep = json.loads(capsys.readouterr().out)
        assert rep["value"] == 1
        assert rep["regressions"][0]["metric"] == "tokens_per_sec"

    def test_rc2_not_enough_history(self, tmp_path):
        _write(tmp_path, "BENCH_r01.json", 100.0)
        rc = bc.main(["--glob", str(tmp_path / "BENCH_r0*.json")])
        assert rc == 2

    def test_rc2_unreadable_candidate(self, tmp_path):
        _write(tmp_path, "BENCH_r01.json", 100.0)
        rc = bc.main(["--glob", str(tmp_path / "BENCH_r0*.json"),
                      "--candidate", str(tmp_path / "missing.json")])
        assert rc == 2

    def test_rc2_candidate_without_metrics(self, tmp_path):
        _write(tmp_path, "BENCH_r01.json", 100.0)
        bad = tmp_path / "cand.json"
        bad.write_text(json.dumps({"rc": 1}))
        rc = bc.main(["--glob", str(tmp_path / "BENCH_r0*.json"),
                      "--candidate", str(bad)])
        assert rc == 2

    def test_explicit_candidate_excluded_from_prior(self, tmp_path):
        """--candidate pointing INTO the history set: the candidate file
        must not anchor itself (it would always compare clean)."""
        _write(tmp_path, "BENCH_r01.json", 100.0)
        cand = _write(tmp_path, "BENCH_r02.json", 50.0)
        rc = bc.main(["--glob", str(tmp_path / "BENCH_r0*.json"),
                      "--candidate", str(cand)])
        assert rc == 1

    def test_failed_runs_skipped_from_history(self, tmp_path):
        """An rc!=0 bootstrap run neither anchors nor crashes the gate."""
        bad = tmp_path / "BENCH_r01.json"
        bad.write_text(json.dumps(
            {"rc": 1, "parsed": {"legs": {"serve":
                                          {"tokens_per_sec": 999.0}}}}))
        _write(tmp_path, "BENCH_r02.json", 100.0)
        _write(tmp_path, "BENCH_r03.json", 95.0)
        rc = bc.main(["--glob", str(tmp_path / "BENCH_r0*.json")])
        assert rc == 0

    def test_tol_for_flag(self, tmp_path):
        hist = {"rc": 0, "parsed": {"legs": {"serve":
                                             {"ttft.p95_ms": 10.0}}}}
        (tmp_path / "BENCH_r01.json").write_text(json.dumps(hist))
        cand = {"rc": 0, "parsed": {"legs": {"serve":
                                             {"ttft.p95_ms": 12.0}}}}
        (tmp_path / "BENCH_r02.json").write_text(json.dumps(cand))
        args = ["--glob", str(tmp_path / "BENCH_r0*.json")]
        assert bc.main(args) == 1
        assert bc.main(args + ["--tol-for", "p95_ms=0.3"]) == 0

    def test_bad_tol_for_spec_errors(self, tmp_path):
        _write(tmp_path, "BENCH_r01.json", 1.0)
        _write(tmp_path, "BENCH_r02.json", 1.0)
        with pytest.raises(SystemExit):
            bc.main(["--glob", str(tmp_path / "BENCH_r0*.json"),
                     "--tol-for", "nonsense"])
