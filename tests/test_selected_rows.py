"""SelectedRows sparse gradients + StringTensor ops.

Reference analogue: test/legacy_test/test_selected_rows.py,
test_sgd_op.py (SelectedRows overloads), test_adam_op.py lazy_mode,
test_strings_lower_upper_op.py.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import SelectedRows


def test_to_dense_accumulates_duplicate_rows():
    sr = SelectedRows(rows=[1, 3, 1], values=np.ones((3, 2), np.float32),
                      height=5)
    d = sr.numpy()
    assert d.shape == (5, 2)
    np.testing.assert_allclose(d[1], 2.0)
    np.testing.assert_allclose(d[3], 1.0)
    np.testing.assert_allclose(d[[0, 2, 4]], 0.0)


def _twin_embeddings(V=10, D=4, seed=0):
    es = paddle.nn.Embedding(V, D, sparse=True)
    ed = paddle.nn.Embedding(V, D, sparse=False)
    with paddle.no_grad():
        ed.weight.set_value(es.weight)
    return es, ed


def test_sparse_embedding_grad_is_selected_rows():
    es, ed = _twin_embeddings()
    ids = paddle.to_tensor(np.array([[1, 2], [2, 7]], np.int64))
    loss_s = (es(ids) ** 2).sum()
    loss_s.backward()
    assert isinstance(es.weight.grad, SelectedRows)

    loss_d = (ed(ids) ** 2).sum()
    loss_d.backward()
    np.testing.assert_allclose(es.weight.grad.numpy(),
                               ed.weight.grad.numpy(), atol=1e-6)
    # only the batch's rows carry gradient mass
    assert sorted(np.asarray(es.weight.grad.rows).tolist()) == [1, 2, 7]


def test_sparse_forward_matches_dense():
    es, ed = _twin_embeddings()
    ids = paddle.to_tensor(np.array([[0, 5, 5], [9, 1, 0]], np.int64))
    np.testing.assert_allclose(es(ids).numpy(), ed(ids).numpy(), atol=1e-6)


def test_padding_idx_respected_in_sparse_path():
    es = paddle.nn.Embedding(8, 3, padding_idx=0, sparse=True)
    ids = paddle.to_tensor(np.array([0, 2], np.int64))
    out = es(ids)
    np.testing.assert_allclose(out.numpy()[0], 0.0, atol=1e-7)


def test_sgd_sparse_step_matches_dense_twin():
    es, ed = _twin_embeddings()
    opt_s = paddle.optimizer.SGD(0.1, parameters=[es.weight])
    opt_d = paddle.optimizer.SGD(0.1, parameters=[ed.weight])
    ids = paddle.to_tensor(np.array([3, 4, 3], np.int64))
    for _ in range(3):
        (es(ids) ** 2).sum().backward()
        opt_s.step()
        opt_s.clear_grad()
        (ed(ids) ** 2).sum().backward()
        opt_d.step()
        opt_d.clear_grad()
    np.testing.assert_allclose(es.weight.numpy(), ed.weight.numpy(),
                               atol=1e-5)


def test_adam_dense_fallback_matches_dense_twin():
    """lazy_mode=False: SelectedRows densifies; trajectory identical to a
    dense gradient (untouched rows' moments still decay)."""
    es, ed = _twin_embeddings()
    opt_s = paddle.optimizer.Adam(0.05, parameters=[es.weight])
    opt_d = paddle.optimizer.Adam(0.05, parameters=[ed.weight])
    ids = paddle.to_tensor(np.array([1, 6], np.int64))
    for _ in range(3):
        (es(ids) ** 2).sum().backward()
        opt_s.step()
        opt_s.clear_grad()
        (ed(ids) ** 2).sum().backward()
        opt_d.step()
        opt_d.clear_grad()
    np.testing.assert_allclose(es.weight.numpy(), ed.weight.numpy(),
                               atol=1e-5)


def test_adam_lazy_mode_freezes_untouched_rows():
    es, _ = _twin_embeddings()
    w0 = es.weight.numpy().copy()
    opt = paddle.optimizer.Adam(0.05, parameters=[es.weight],
                                lazy_mode=True)
    ids = paddle.to_tensor(np.array([2, 5], np.int64))
    for _ in range(2):
        (es(ids) ** 2).sum().backward()
        opt.step()
        opt.clear_grad()
    w1 = es.weight.numpy()
    touched = [2, 5]
    untouched = [i for i in range(10) if i not in touched]
    # untouched rows identical; touched rows moved
    np.testing.assert_allclose(w1[untouched], w0[untouched], atol=1e-7)
    assert np.abs(w1[touched] - w0[touched]).max() > 1e-4


def test_grad_accumulation_concats_rows():
    es, ed = _twin_embeddings()
    ids1 = paddle.to_tensor(np.array([1, 2], np.int64))
    ids2 = paddle.to_tensor(np.array([2, 3], np.int64))
    (es(ids1) ** 2).sum().backward()
    (es(ids2) ** 2).sum().backward()
    (ed(ids1) ** 2).sum().backward()
    (ed(ids2) ** 2).sum().backward()
    np.testing.assert_allclose(es.weight.grad.numpy(),
                               ed.weight.grad.numpy(), atol=1e-6)


class TestStrings:
    def test_lower_upper(self):
        from paddle_tpu.text import strings
        st = strings.to_string_tensor([["Hello", "WORLD"], ["TPU", "ok"]])
        assert st.shape == [2, 2]
        assert strings.lower(st).tolist() == [["hello", "world"],
                                              ["tpu", "ok"]]
        assert strings.upper(st).tolist() == [["HELLO", "WORLD"],
                                              ["TPU", "OK"]]

    def test_empty_and_like(self):
        from paddle_tpu.text import strings
        e = strings.empty([2, 3])
        assert e.shape == [2, 3] and e.tolist()[0][0] == ""
        el = strings.empty_like(e)
        assert el.shape == [2, 3]

    def test_unicode(self):
        from paddle_tpu.text import strings
        st = strings.to_string_tensor(["Grüße"])
        assert strings.upper(st, use_utf8_encoding=True).tolist() == \
            ["GRÜSSE"]


def test_viterbi_decoder_layer():
    from paddle_tpu.text import ViterbiDecoder
    rng = np.random.RandomState(0)
    pot = paddle.to_tensor(rng.rand(1, 3, 4).astype(np.float32))
    trans = paddle.to_tensor(rng.rand(4, 4).astype(np.float32))
    dec = ViterbiDecoder(trans, include_bos_eos_tag=False)
    scores, path = dec(pot)
    assert tuple(np.asarray(path.numpy()).shape)[-1] == 3


class TestReviewedEdges:
    def test_mixed_sparse_dense_grad_merges(self):
        """Weight used both through the sparse lookup and directly: grads
        merge to dense instead of crashing/overwriting."""
        es, ed = _twin_embeddings()
        ids = paddle.to_tensor(np.array([1, 2], np.int64))
        loss = (es(ids) ** 2).sum() + (es.weight ** 2).sum()
        loss.backward()
        loss_d = (ed(ids) ** 2).sum() + (ed.weight ** 2).sum()
        loss_d.backward()
        assert not isinstance(es.weight.grad, SelectedRows)
        np.testing.assert_allclose(es.weight.grad.numpy(),
                                   ed.weight.grad.numpy(), atol=1e-6)

    def test_paddle_grad_does_not_touch_weight_grad(self):
        es, _ = _twin_embeddings()
        ids = paddle.to_tensor(np.array([1, 2], np.int64))
        loss = (es(ids) ** 2).sum()
        with pytest.raises(RuntimeError, match="unused"):
            paddle.grad(loss, [es.weight])
        assert es.weight.grad is None

    def test_adamw_lazy_mode_applies_decay_to_touched_rows(self):
        es, _ = _twin_embeddings()
        w0 = es.weight.numpy().copy()
        opt = paddle.optimizer.AdamW(0.1, parameters=[es.weight],
                                     weight_decay=0.5, lazy_mode=True)
        ids = paddle.to_tensor(np.array([3], np.int64))
        (es(ids) ** 2).sum().backward()
        opt.step()
        # no-decay twin
        es2, _ = _twin_embeddings()
        with paddle.no_grad():
            es2.weight.set_value(paddle.to_tensor(w0))
        opt2 = paddle.optimizer.AdamW(0.1, parameters=[es2.weight],
                                      weight_decay=0.0, lazy_mode=True)
        (es2(ids) ** 2).sum().backward()
        opt2.step()
        # decay must move row 3 beyond the pure-adam update
        assert np.abs(es.weight.numpy()[3]
                      - es2.weight.numpy()[3]).max() > 1e-4
        # untouched rows are identical (and undecayed) in both
        np.testing.assert_allclose(es.weight.numpy()[4], w0[4], atol=1e-7)

    def test_adam_amsgrad_lazy_falls_back_to_dense_semantics(self):
        es, ed = _twin_embeddings()
        opt_s = paddle.optimizer.Adam(0.05, parameters=[es.weight],
                                      lazy_mode=True, amsgrad=True)
        opt_d = paddle.optimizer.Adam(0.05, parameters=[ed.weight],
                                      amsgrad=True)
        ids = paddle.to_tensor(np.array([1, 6], np.int64))
        for _ in range(2):
            (es(ids) ** 2).sum().backward()
            opt_s.step()
            opt_s.clear_grad()
            (ed(ids) ** 2).sum().backward()
            opt_d.step()
            opt_d.clear_grad()
        np.testing.assert_allclose(es.weight.numpy(), ed.weight.numpy(),
                                   atol=1e-5)

    def test_clear_gradient_set_to_zero_on_selected_rows(self):
        es, _ = _twin_embeddings()
        ids = paddle.to_tensor(np.array([1], np.int64))
        (es(ids) ** 2).sum().backward()
        assert isinstance(es.weight.grad, SelectedRows)
        es.weight.clear_gradient(set_to_zero=True)
        np.testing.assert_allclose(es.weight.grad.numpy(), 0.0)
        assert es.weight.grad.numpy().shape == tuple(es.weight.shape)

    def test_sparse_accepts_array_like_input(self):
        es, ed = _twin_embeddings()
        out = es(np.array([1, 2], np.int64))
        np.testing.assert_allclose(
            out.numpy(), ed(np.array([1, 2], np.int64)).numpy(), atol=1e-6)
