"""Elastic serving fleet (paddle_tpu.serving.fleet / .router).

The load-bearing contract: ZERO LOST REQUESTS UNDER CHURN — every
admitted request reaches a terminal ``finish_reason`` whatever replicas
crash or stall — and, with no faults injected, fleet output is
token-identical to a single ``LLMEngine`` (itself token-identical to
sequential ``GPT.generate``).  Plus the routing/shedding policy surface:
least-outstanding-tokens dispatch, SLO-aware ``RetryAfter`` shedding,
heartbeat stall detection, warmed respawn, at-most-once re-prefill with
deterministic token replay."""

import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.profiler import counters
from paddle_tpu.resilience import faultinject


@pytest.fixture(scope="module")
def model():
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=32,
                    use_flash_attention=False)
    paddle.seed(31)
    m = GPTForCausalLM(cfg)
    m.eval()
    return m


def _fleet(m, **kw):
    from paddle_tpu.serving import ServingFleet
    kw.setdefault("replicas", 2)
    kw.setdefault("threaded", False)
    kw.setdefault("max_slots", 2)
    kw.setdefault("max_seq_len", 32)
    kw.setdefault("min_bucket", 4)
    kw.setdefault("queue_size", 16)
    kw.setdefault("heartbeat_timeout_s", 30.0)
    return ServingFleet(m, **kw)


def _ref(m, prompt, max_new, **kw):
    """Sequential reference: the request alone through GPT.generate."""
    out = np.asarray(m.generate(paddle.to_tensor(np.asarray([prompt])),
                                max_new_tokens=max_new, **kw).numpy())[0]
    return out[len(prompt):]


@pytest.mark.slow
class TestNoFaultIdentity:
    def test_greedy_token_identical_to_single_engine(self, model):
        rng = np.random.default_rng(0)
        prompts = [rng.integers(0, 64, size=n).tolist()
                   for n in (5, 3, 9, 6, 11)]
        refs = [_ref(model, p, 6) for p in prompts]
        fleet = _fleet(model)
        hs = [fleet.submit(p, max_new_tokens=6) for p in prompts]
        fleet.join(hs)
        for h, r in zip(hs, refs):
            assert np.array_equal(h.tokens, r), (h.tokens, list(r))
            assert h.finish_reason == "length"
            assert h.retries == 0
        fleet.drain()
        assert counters.get("serving.fleet.lost") == 0

    def test_sampled_token_identical_with_seeds(self, model):
        """Per-request seeds survive routing: whatever replica serves a
        request, its PRNG chain (and tokens) match the solo run."""
        rng = np.random.default_rng(1)
        prompts = [rng.integers(0, 64, size=n).tolist() for n in (4, 7, 11)]
        kw = dict(do_sample=True, temperature=0.8, top_k=8, top_p=0.9)
        refs = [_ref(model, p, 5, seed=100 + i, **kw)
                for i, p in enumerate(prompts)]
        fleet = _fleet(model, max_slots=1)
        outs = fleet.generate(prompts, seeds=[100 + i for i in range(3)],
                              max_new_tokens=5, **kw)
        for o, p, r in zip(outs, prompts, refs):
            assert np.array_equal(o, list(p) + list(r))
        fleet.drain()


class TestRouter:
    @pytest.mark.slow
    def test_least_outstanding_tokens_dispatch(self, model):
        """Load is the undelivered-token backlog, not the request count:
        the second request avoids the replica owing 20 tokens."""
        fleet = _fleet(model, replicas=2, max_slots=1)
        h0 = fleet.submit([1, 2, 3], max_new_tokens=20)
        h1 = fleet.submit([4, 5, 6], max_new_tokens=2)
        h2 = fleet.submit([7, 8, 9], max_new_tokens=2)
        assert h0.replica_idx != h1.replica_idx
        # h1's replica owes 2 tokens vs h0's 20 → h2 joins h1's replica
        assert h2.replica_idx == h1.replica_idx
        fleet.join([h0, h1, h2])
        fleet.drain()

    def test_slo_shed_returns_structured_retry_after(self, model):
        """Once a decode tokens/s EMA exists, a request whose deadline
        budget is blown by the estimated completion time is shed with a
        RetryAfter carrying queue_depth + retry_after_hint."""
        from paddle_tpu.serving import RetryAfter
        rng = np.random.default_rng(2)
        prompts = [rng.integers(0, 64, size=5).tolist() for _ in range(2)]
        fleet = _fleet(model, replicas=1)
        fleet.generate(prompts, max_new_tokens=4)   # primes the EMA
        assert fleet.stats()["decode_tps"] > 0
        before = counters.snapshot()
        with pytest.raises(RetryAfter) as ei:
            fleet.submit(prompts[0], max_new_tokens=16, deadline_s=1e-6)
        assert ei.value.reason == "slo"
        assert ei.value.queue_depth >= 0
        assert ei.value.retry_after_hint is not None
        assert ei.value.retry_after_hint >= 0.0
        d = counters.delta(before)
        assert d.get("serving.fleet.shed", 0) == 1
        assert d.get("serving.fleet.dispatched", 0) == 0
        # no deadline → no shedding, the same request is admitted
        h = fleet.submit(prompts[0], max_new_tokens=16)
        fleet.join([h])
        assert h.finish_reason == "length"
        fleet.drain()

    @pytest.mark.slow
    def test_cold_fleet_admits_with_deadline(self, model):
        """No EMA yet → no shedding: the deadline is enforced by the
        engine, not guessed by the router."""
        fleet = _fleet(model, replicas=1)
        h = fleet.submit([1, 2, 3, 4], max_new_tokens=4, deadline_s=60.0)
        fleet.join([h])
        assert h.finish_reason == "length"
        fleet.drain()

    def test_router_queue_fault_is_structured_shed(self, model):
        from paddle_tpu.serving import RetryAfter
        fleet = _fleet(model)
        # the NEXT fleet rid is deterministic: count submissions so far
        with faultinject.fault_schedule("router_queue@0"):
            with pytest.raises(RetryAfter) as ei:
                fleet.submit([1, 2, 3], max_new_tokens=2)
            assert ei.value.reason == "router_queue"
            assert faultinject.fired == [("router_queue", 0)]
        # the fleet keeps serving afterwards
        h = fleet.submit([1, 2, 3], max_new_tokens=2)
        fleet.join([h])
        assert h.finish_reason == "length"
        fleet.drain()


class TestChaos:
    def test_crash_and_stall_zero_lost(self, model):
        """THE chaos gate: a deterministic schedule kills one replica
        mid-decode (replica_crash) and hangs the other (decode_stall,
        caught by the heartbeat stall detector).  Every request reaches a
        terminal finish_reason, retried == injected faults, respawns ==
        replica deaths, zero lost, and the delivered tokens still match
        the solo trajectories exactly (deterministic replay)."""
        rng = np.random.default_rng(3)
        p0 = rng.integers(0, 64, size=5).tolist()
        p1 = rng.integers(0, 64, size=6).tolist()   # same bucket as p0
        r0, r1 = _ref(model, p0, 6), _ref(model, p1, 6)
        fleet = _fleet(model, max_slots=1, heartbeat_timeout_s=0.05,
                       warm_buckets=(5,))
        h0 = fleet.submit(p0, max_new_tokens=6)
        h1 = fleet.submit(p1, max_new_tokens=6)
        assert h0.replica_idx != h1.replica_idx
        before = counters.snapshot()
        with faultinject.fault_schedule(
                f"replica_crash@{h0.rid};decode_stall@{h1.rid}"):
            fleet.pump()              # admits both (prefill, 1st token)
            fleet.pump()              # crash fires on h0's replica;
            # stall freezes h1's replica: heartbeats stop
            time.sleep(0.08)          # stall detector window elapses
            fleet.join([h0, h1], timeout_s=120)
            assert sorted(faultinject.fired) == [
                ("decode_stall", h1.rid), ("replica_crash", h0.rid)]
        d = counters.delta(before)
        assert h0.finish_reason == "length"
        assert h1.finish_reason == "length"
        assert np.array_equal(h0.tokens, r0)
        assert np.array_equal(h1.tokens, r1)
        assert h0.retries == 1 and h1.retries == 1
        assert d.get("serving.fleet.retried", 0) == 2      # == faults
        assert d.get("serving.fleet.respawns", 0) == 2     # crash + stall
        assert d.get("serving.fleet.replica_deaths.crash", 0) == 1
        assert d.get("serving.fleet.replica_deaths.stall", 0) == 1
        assert d.get("serving.fleet.heartbeat_misses", 0) == 1
        assert d.get("serving.fleet.lost", 0) == 0
        assert d.get("serving.fleet.replayed_tokens", 0) >= 2
        fleet.drain()
        assert counters.get("serving.fleet.lost") == 0

    @pytest.mark.slow
    def test_retry_is_at_most_once_then_surfaced(self, model):
        """A request whose replica dies TWICE has burned its re-prefill
        budget: it is surfaced as finish_reason='retried' with the partial
        tokens delivered so far — never silently lost, never replayed a
        second time."""
        rng = np.random.default_rng(4)
        p = rng.integers(0, 64, size=5).tolist()
        ref = _ref(model, p, 6)
        fleet = _fleet(model, replicas=2, max_slots=1, warm_buckets=(5,))
        h = fleet.submit(p, max_new_tokens=6)
        before = counters.snapshot()
        with faultinject.fault_schedule(f"replica_crash@{h.rid}*2"):
            fleet.join([h], timeout_s=120)
        d = counters.delta(before)
        assert h.finish_reason == "retried"
        assert h.retries == 1                       # at-most-once
        assert d.get("serving.fleet.retried", 0) == 1
        assert d.get("serving.fleet.respawns", 0) == 2
        # the partial stream is a prefix of the solo trajectory
        assert np.array_equal(h.tokens, ref[:len(h.tokens)])
        assert d.get("serving.fleet.lost", 0) == 0
        fleet.drain()

    @pytest.mark.slow
    def test_queued_requests_on_dead_replica_are_requeued(self, model):
        """A crash strands queued work too: requests waiting in the dead
        replica's admission queue are re-dispatched, not lost."""
        rng = np.random.default_rng(5)
        prompts = [rng.integers(0, 64, size=4).tolist() for _ in range(4)]
        refs = [_ref(model, p, 4) for p in prompts]
        fleet = _fleet(model, replicas=2, max_slots=1, queue_size=8,
                       warm_buckets=(4,))
        hs = [fleet.submit(p, max_new_tokens=4) for p in prompts]
        before = counters.snapshot()
        with faultinject.fault_schedule(f"replica_crash@{hs[0].rid}"):
            fleet.join(hs, timeout_s=120)
        d = counters.delta(before)
        assert [h.finish_reason for h in hs] == ["length"] * 4
        for h, r in zip(hs, refs):
            assert np.array_equal(h.tokens, r)
        assert d.get("serving.fleet.respawns", 0) == 1
        assert d.get("serving.fleet.retried", 0) >= 1
        assert d.get("serving.fleet.lost", 0) == 0
        fleet.drain()

    @pytest.mark.slow
    def test_respawned_replica_is_warm_no_steady_retraces(self, model):
        """warm_buckets pre-compiles every replica's programs, so even
        the FIRST request after a respawn retraces nothing — the fresh
        replica compiled its bucketed prefill + decode programs before
        rejoining dispatch."""
        rng = np.random.default_rng(6)
        prompts = [rng.integers(0, 64, size=5).tolist() for _ in range(3)]
        fleet = _fleet(model, replicas=2, max_slots=1, warm_buckets=(5,))
        before = counters.snapshot()
        hs = [fleet.submit(p, max_new_tokens=3) for p in prompts]
        fleet.join(hs)
        assert counters.delta(before).get("serving.retraces", 0) == 0
        h = fleet.submit(prompts[0], max_new_tokens=3)
        with faultinject.fault_schedule(f"replica_crash@{h.rid}"):
            fleet.join([h], timeout_s=120)
        assert h.finish_reason == "length"
        # post-churn steady state: the respawned replica serves warm
        before = counters.snapshot()
        hs = [fleet.submit(p, max_new_tokens=3) for p in prompts]
        fleet.join(hs)
        assert counters.delta(before).get("serving.retraces", 0) == 0
        fleet.drain()

    @pytest.mark.slow
    def test_cancel_during_churn_terminates(self, model):
        """Cancellation races a retry: the request still reaches exactly
        one terminal state (cancelled), never resurrects."""
        rng = np.random.default_rng(7)
        p = rng.integers(0, 64, size=5).tolist()
        fleet = _fleet(model, replicas=2, max_slots=1, warm_buckets=(5,))
        h = fleet.submit(p, max_new_tokens=8)
        with faultinject.fault_schedule(f"replica_crash@{h.rid}"):
            fleet.pump()
            fleet.pump()    # crash + requeue
            h.cancel()
            fleet.join([h], timeout_s=120)
        assert h.finish_reason in ("cancelled", "retried", "length")
        assert h.is_finished
        fleet.drain()
        assert counters.get("serving.fleet.lost") == 0


@pytest.mark.slow
class TestThreaded:
    def test_threaded_completes_and_drains(self, model):
        from paddle_tpu.serving import EngineClosed
        rng = np.random.default_rng(8)
        prompts = [rng.integers(0, 64, size=n).tolist()
                   for n in (5, 3, 6, 4, 7)]
        refs = [_ref(model, p, 4) for p in prompts]
        fleet = _fleet(model, threaded=True, warm_buckets=(5, 3, 6, 4, 7))
        hs = [fleet.submit(p, max_new_tokens=4) for p in prompts]
        for h in hs:
            assert h.wait(timeout=120)
        for h, r in zip(hs, refs):
            assert h.finish_reason == "length"
            assert np.array_equal(h.tokens, r)
        fleet.drain()
        with pytest.raises(EngineClosed):
            fleet.submit([1, 2], max_new_tokens=2)

    def test_threaded_crash_recovery(self, model):
        """Worker-thread crash flows through the same drain/respawn path:
        all requests terminal, zero lost, one respawn."""
        rng = np.random.default_rng(9)
        prompts = [rng.integers(0, 64, size=5).tolist() for _ in range(4)]
        refs = [_ref(model, p, 5) for p in prompts]
        fleet = _fleet(model, threaded=True, max_slots=1,
                       warm_buckets=(5,))
        before = counters.snapshot()
        with faultinject.fault_schedule("replica_crash@0"):
            hs = [fleet.submit(p, max_new_tokens=5) for p in prompts]
            fleet.join(hs, timeout_s=120)
        d = counters.delta(before)
        assert all(h.finish_reason == "length" for h in hs)
        for h, r in zip(hs, refs):
            assert np.array_equal(h.tokens, r)
        assert d.get("serving.fleet.respawns", 0) == 1
        assert d.get("serving.fleet.lost", 0) == 0
        fleet.drain()


@pytest.mark.slow
class TestFleetSurface:
    def test_stats_and_gauges(self, model):
        fleet = _fleet(model)
        h = fleet.submit([1, 2, 3, 4], max_new_tokens=3)
        fleet.join([h])
        st = fleet.stats()
        assert st["alive"] == 2
        assert st["requests"] == 1 and st["unfinished"] == 0
        assert len(st["replicas"]) == 2
        for rs in st["replicas"]:
            assert {"idx", "alive", "outstanding_tokens",
                    "decode_tps_ema"} <= set(rs)
        assert st["decode_tps"] >= 0
        assert counters.get("serving.fleet.replicas") == 2
        fleet.drain()
        assert counters.get("serving.fleet.replicas") == 0
        assert fleet.stats()["closed"]

    def test_generate_blocking_api(self, model):
        rng = np.random.default_rng(10)
        prompts = [rng.integers(0, 64, size=n).tolist() for n in (4, 6, 3)]
        refs = [_ref(model, p, 4) for p in prompts]
        fleet = _fleet(model)
        outs = fleet.generate(prompts, max_new_tokens=4)
        for o, p, r in zip(outs, prompts, refs):
            assert np.array_equal(o, list(p) + list(r))
        fleet.drain()

    def test_backpressure_when_every_queue_full(self, model):
        from paddle_tpu.serving import RetryAfter
        fleet = _fleet(model, replicas=2, max_slots=1, queue_size=1)
        hs = [fleet.submit([1, 2, 3], max_new_tokens=8)
              for _ in range(2)]   # one queued per replica: both full
        with pytest.raises(RetryAfter) as ei:
            fleet.submit([1, 2, 3], max_new_tokens=8)
        assert ei.value.reason == "backpressure"
        assert ei.value.queue_depth >= 1
        fleet.join(hs)
        fleet.drain()
