"""Model zoo + data pipeline + hapi tests."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def np_t(x):
    return np.asarray(x.numpy())


class TestDataLoader:
    def test_basic(self):
        from paddle_tpu.io import DataLoader, Dataset

        class DS(Dataset):
            def __len__(self):
                return 10

            def __getitem__(self, i):
                return np.full((3,), i, np.float32), i

        loader = DataLoader(DS(), batch_size=4, drop_last=False)
        batches = list(loader)
        assert len(batches) == 3
        x, y = batches[0]
        assert x.shape == [4, 3]
        assert np_t(y).tolist() == [0, 1, 2, 3]

    def test_shuffle_and_workers(self):
        from paddle_tpu.io import DataLoader, Dataset

        class DS(Dataset):
            def __len__(self):
                return 20

            def __getitem__(self, i):
                return np.asarray([i], np.float32)

        loader = DataLoader(DS(), batch_size=5, shuffle=True, num_workers=2)
        seen = []
        for (x,) in [(b,) for b in loader]:
            seen.extend(np_t(x).reshape(-1).tolist())
        assert sorted(seen) == list(range(20))

    def test_samplers(self):
        from paddle_tpu.io import (BatchSampler, DistributedBatchSampler,
                                   RandomSampler, SequenceSampler)

        class DS:
            def __len__(self):
                return 10

            def __getitem__(self, i):
                return i

        bs = BatchSampler(DS(), batch_size=3, drop_last=True)
        assert len(bs) == 3
        dbs = DistributedBatchSampler(DS(), batch_size=2, num_replicas=2,
                                      rank=0)
        idx = [i for batch in dbs for i in batch]
        assert all(i % 2 == 0 or True for i in idx)
        assert len(idx) == 5

    def test_tensor_dataset_random_split(self):
        from paddle_tpu.io import TensorDataset, random_split
        x = paddle.randn([10, 2])
        y = paddle.arange(10)
        ds = TensorDataset([x, y])
        assert len(ds) == 10
        a, b = random_split(ds, [7, 3])
        assert len(a) == 7 and len(b) == 3


class TestVisionModels:
    def test_lenet_forward_train(self):
        from paddle_tpu.vision.models import LeNet
        net = LeNet()
        x = paddle.randn([2, 1, 28, 28])
        out = net(x)
        assert out.shape == [2, 10]
        loss = nn.CrossEntropyLoss()(out, paddle.to_tensor([1, 2]))
        loss.backward()
        opt = paddle.optimizer.SGD(0.1, parameters=net.parameters())
        opt.step()

    @pytest.mark.slow
    def test_resnet18_forward(self):
        from paddle_tpu.vision.models import resnet18
        net = resnet18(num_classes=10)
        out = net(paddle.randn([1, 3, 32, 32]))
        assert out.shape == [1, 10]

    @pytest.mark.parametrize("family", [
        "mobilenet_v1", "mobilenet_v2", "mobilenet_v3_small",
        "mobilenet_v3_large", "shufflenet_v2_x0_5", "densenet121",
        "googlenet", "inception_v3"])
    def test_all_families_forward(self, family):
        """Every reference vision family (vision/models/) builds and runs
        a forward at ImageNet-ish resolution."""
        import paddle_tpu.vision.models as M
        net = getattr(M, family)(num_classes=7)
        net.eval()
        size = 299 if family == "inception_v3" else 224
        out = net(paddle.randn([1, 3, size, size]))
        assert out.shape == [1, 7], family


class TestGPTSingle:
    def test_forward_and_train(self):
        from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                       GPTPretrainingCriterion)
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=2, max_seq_len=16,
                        use_flash_attention=False)
        model = GPTForCausalLM(cfg)
        crit = GPTPretrainingCriterion()
        ids = paddle.randint(0, 64, [2, 16])
        logits = model(ids)
        assert logits.shape == [2, 16, 64]
        opt = paddle.optimizer.AdamW(1e-2, parameters=model.parameters())
        l0 = None
        for i in range(5):
            loss = crit(model(ids), ids)
            loss.backward()
            opt.step()
            opt.clear_grad()
            if l0 is None:
                l0 = float(loss.numpy())
        assert float(loss.numpy()) < l0

    def test_rope_variant(self):
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        cfg = GPTConfig(vocab_size=32, hidden_size=32, num_layers=1,
                        num_heads=2, max_seq_len=8, use_rope=True,
                        use_flash_attention=False)
        out = GPTForCausalLM(cfg)(paddle.randint(0, 32, [1, 8]))
        assert out.shape == [1, 8, 32]

    def test_recompute_parity(self):
        from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                       GPTPretrainingCriterion)
        paddle.seed(3)
        cfg = GPTConfig(vocab_size=32, hidden_size=16, num_layers=2,
                        num_heads=2, max_seq_len=8, recompute=False,
                        use_flash_attention=False)
        m1 = GPTForCausalLM(cfg)
        cfg2 = GPTConfig(vocab_size=32, hidden_size=16, num_layers=2,
                         num_heads=2, max_seq_len=8, recompute=True,
                         use_flash_attention=False)
        m2 = GPTForCausalLM(cfg2)
        m2.set_state_dict(m1.state_dict())
        ids = paddle.randint(0, 32, [1, 8])
        o1, o2 = m1(ids), m2(ids)
        assert np.allclose(np_t(o1), np_t(o2), atol=1e-5)


class TestBert:
    def test_bert_forward(self):
        from paddle_tpu.models import BertConfig, BertModel
        cfg = BertConfig(vocab_size=64, hidden_size=32, num_hidden_layers=2,
                         num_attention_heads=2, intermediate_size=64,
                         max_position_embeddings=16,
                         hidden_dropout_prob=0.0,
                         attention_probs_dropout_prob=0.0)
        model = BertModel(cfg)
        seq, pooled = model(paddle.randint(0, 64, [2, 8]))
        assert seq.shape == [2, 8, 32]
        assert pooled.shape == [2, 32]

    def test_bert_pretrain_loss(self):
        from paddle_tpu.models import BertConfig, BertForPretraining
        from paddle_tpu.models.bert import BertPretrainingCriterion
        cfg = BertConfig(vocab_size=64, hidden_size=32, num_hidden_layers=1,
                         num_attention_heads=2, intermediate_size=64,
                         max_position_embeddings=16,
                         hidden_dropout_prob=0.0,
                         attention_probs_dropout_prob=0.0)
        model = BertForPretraining(cfg)
        crit = BertPretrainingCriterion()
        ids = paddle.randint(0, 64, [2, 8])
        logits, nsp = model(ids)
        loss = crit(logits, nsp, ids, paddle.to_tensor([0, 1]))
        assert np.isfinite(float(loss.numpy()))
        loss.backward()


class TestHapi:
    def test_model_fit(self):
        from paddle_tpu.io import Dataset

        class DS(Dataset):
            def __len__(self):
                return 32

            def __getitem__(self, i):
                x = np.random.randn(4).astype(np.float32)
                return x, np.int64(i % 2)

        net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        model = paddle.Model(net)
        model.prepare(paddle.optimizer.Adam(0.01,
                                            parameters=net.parameters()),
                      nn.CrossEntropyLoss(),
                      paddle.metric.Accuracy())
        model.fit(DS(), epochs=1, batch_size=8, verbose=0)
        res = model.evaluate(DS(), batch_size=8, verbose=0)
        assert "loss" in res

    def test_summary(self):
        net = nn.Linear(4, 2)
        info = paddle.summary(net)
        assert info["total_params"] == 10


class TestMetrics:
    def test_accuracy(self):
        m = paddle.metric.Accuracy()
        pred = paddle.to_tensor([[0.9, 0.1], [0.2, 0.8]])
        lab = paddle.to_tensor([[0], [1]])
        m.update(m.compute(pred, lab))
        assert m.accumulate() == 1.0

    def test_precision_recall_auc(self):
        p = paddle.metric.Precision()
        r = paddle.metric.Recall()
        preds = paddle.to_tensor([0.9, 0.4, 0.8, 0.1])
        labels = paddle.to_tensor([1, 0, 0, 1])
        p.update(preds, labels)
        r.update(preds, labels)
        assert abs(p.accumulate() - 0.5) < 1e-6
        assert abs(r.accumulate() - 0.5) < 1e-6


class TestAmpIntegration:
    def test_bf16_training(self):
        net = nn.Linear(4, 4)
        opt = paddle.optimizer.SGD(0.1, parameters=net.parameters())
        x = paddle.randn([2, 4])
        with paddle.amp.auto_cast(level="O1"):
            loss = net(x).mean()
        loss.backward()
        opt.step()
        assert net.weight.grad is None or True  # step consumed grads


class TestFlops:
    def test_flops_matches_matmul_count(self):
        """paddle.flops via XLA cost analysis ~= analytic 2*M*N*K."""
        net = nn.Sequential(nn.Linear(64, 128), nn.ReLU(),
                            nn.Linear(128, 10))
        got = paddle.flops(net, [4, 64])
        expect = 2 * (64 * 128 + 128 * 10) * 4
        assert abs(got - expect) / expect < 0.05, (got, expect)
