"""Auto-generated parity coverage for the YAML op corpus (L3 codegen).

Every entry in paddle_tpu/ops/ops.yaml gets one OpTest-style parity case
built from its `sample`/`ref` fields — numpy reference vs eager vs jit vs
dp-sharded, plus the numeric-vs-analytic gradient check — so an op added to
the YAML is covered from birth (the reference enforces the same invariant by
requiring a test_*_op.py per ops.yaml entry, test/legacy_test/).

Also locks the codegen pipeline itself:
  - _generated.py must match the YAML (scripts/gen_ops.py --check),
  - infer_meta (jax.eval_shape) must agree with real execution,
  - the SPMD_RULES table must agree with GSPMD's actual output shardings
    on the 8-virtual-device mesh.
"""

import subprocess
import sys
import zlib

import numpy as np
import pytest
import scipy.special as sps  # noqa: F401  (ref-expr namespace)

import paddle_tpu as paddle
from paddle_tpu import ops as pops
from paddle_tpu.ops import OP_SPECS

from op_harness import OpCase, run_case

_REF_NS = {"np": np, "sps": sps}


def _resolve_ref(expr):
    return eval(expr, dict(_REF_NS))  # specs are repo-authored code fragments


def _n_inputs(spec):
    return 2 if spec["template"] in ("binary", "logic_binary") else 1


def _build_inputs(spec):
    sample = spec.get("sample", {}) or {}
    n = _n_inputs(spec)
    shapes = sample.get("shapes", [[8, 4]] * n)
    rng = np.random.RandomState(zlib.crc32(spec["op"].encode()) % (2**31))
    lo, hi = sample.get("domain", [-1.0, 1.0])
    int_range = sample.get("int_range", [0, 8])
    int_inputs = list(sample.get("int_inputs", []))
    if sample.get("int"):
        int_inputs = list(range(n))
    inputs = []
    for i, shp in enumerate(shapes):
        if i in int_inputs:
            x = rng.randint(int_range[0], int_range[1] + 1,
                            size=shp).astype(np.int32)
        else:
            x = rng.uniform(lo, hi, size=shp).astype(np.float32)
        inputs.append(x)
    specials = sample.get("specials")
    if specials:
        x = inputs[0]
        flat = x.reshape(-1)
        flat[0] = np.nan
        if specials is not True and specials == "nan":
            flat[1] = np.nan
        else:
            flat[1], flat[2] = np.inf, -np.inf
        inputs[0] = flat.reshape(x.shape)
    return inputs, int_inputs


def _make_case(op, spec):
    sample = spec.get("sample", {}) or {}
    inputs, int_inputs = _build_inputs(spec)
    grad = spec.get("grad", True) and not sample.get("int")
    return OpCase(
        name=op,
        fn=getattr(pops, op),
        ref=_resolve_ref(spec["ref"]),
        inputs=inputs,
        kwargs=dict(sample.get("kwargs", {})),
        dtypes=tuple(sample.get("dtypes", ("float32", "bfloat16",
                                           "float16"))),
        grad=grad,
        integer_inputs=tuple(int_inputs),
    )


@pytest.mark.parametrize("op", sorted(OP_SPECS))
def test_yaml_op_parity(op):
    spec = OP_SPECS[op]
    run_case(_make_case(op, spec))


def test_generated_file_matches_yaml():
    """The checked-in _generated.py must be exactly what the YAML produces
    (single-source-of-truth guard)."""
    r = subprocess.run([sys.executable, "scripts/gen_ops.py", "--check"],
                       cwd=str(__import__("pathlib").Path(
                           __file__).resolve().parent.parent),
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr


def test_infer_meta_matches_execution():
    """jax.eval_shape-based infer_meta == real output shape/dtype."""
    import jax

    checked = 0
    for op, spec in OP_SPECS.items():
        if op not in pops.META or spec.get("sample", {}).get("kwargs"):
            continue
        if spec["template"] not in ("unary", "binary"):
            continue
        inputs, _ = _build_inputs(spec)
        meta = pops.infer_meta(
            op, *[jax.ShapeDtypeStruct(x.shape, x.dtype) for x in inputs])
        out = getattr(pops, op)(*[paddle.to_tensor(x) for x in inputs])
        assert tuple(meta.shape) == tuple(out.shape), op
        assert str(meta.dtype) == str(out.numpy().dtype), op
        checked += 1
    assert checked > 50


def test_reduction_infer_meta_keepdim():
    import jax

    m = pops.infer_meta("sum", jax.ShapeDtypeStruct((8, 4), np.float32),
                        axis=1, keepdim=True)
    assert tuple(m.shape) == (8, 1)
    m = pops.infer_meta("mean", jax.ShapeDtypeStruct((8, 4), np.float32),
                        axis=0)
    assert tuple(m.shape) == (4,)


class TestSpmdRules:
    """SPMD_RULES predictions vs GSPMD ground truth on the 8-device mesh."""

    def _gspmd_out_spec(self, fn, arrays, in_specs):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding

        # local mesh only — registering a global dp-mesh leaks into later
        # single-chip tests (see op_harness._run_sharded)
        mesh = Mesh(np.array(jax.devices()), ("dp",))
        placed = [jax.device_put(jnp.asarray(a), NamedSharding(mesh, s))
                  for a, s in zip(arrays, in_specs)]
        out = jax.jit(fn)(*placed)
        return out.sharding.spec, mesh

    def test_elementwise_propagates_dp(self):
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        x = np.random.rand(8, 4).astype(np.float32)
        y = np.random.rand(8, 4).astype(np.float32)
        got, _ = self._gspmd_out_spec(jnp.add, [x, y], [P("dp"), P("dp")])
        want = pops.propagate("add", [P("dp"), P("dp")], [2, 2])
        assert tuple(got) + (None,) * (2 - len(tuple(got))) == \
            tuple(want) + (None,) * (2 - len(tuple(want)))

    def test_reduction_keeps_batch_dim(self):
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        x = np.random.rand(8, 4).astype(np.float32)
        got, _ = self._gspmd_out_spec(
            lambda v: jnp.sum(v, axis=1), [x], [P("dp", None)])
        want = pops.propagate("sum", [P("dp", None)], [2], axis=1)
        assert tuple(got) == tuple(want)

    def test_reduction_over_sharded_dim_replicates(self):
        from jax.sharding import PartitionSpec as P

        want = pops.propagate("sum", [P("dp", None)], [2], axis=0)
        # the dp sharding on the reduced dim is consumed; survivor dim is
        # unsharded
        assert tuple(want) == (None,)

    def test_matmul_contraction_consumed(self):
        from jax.sharding import PartitionSpec as P

        from paddle_tpu.ops.spmd import matmul
        # (m,k) sharded on k × (k,n) sharded on k: contraction consumes the
        # k sharding (GSPMD emits the all-reduce); output is (m-spec, n-spec)
        want = matmul([P(None, "mp"), P("mp", None)], [2, 2])
        assert tuple(want) == (None, None)

    def test_conflicting_shardings_rejected(self):
        from jax.sharding import PartitionSpec as P

        from paddle_tpu.ops.spmd import elementwise
        with pytest.raises(ValueError):
            elementwise([P("dp"), P("mp")], [1, 1])

    def test_broadcast_alignment(self):
        from jax.sharding import PartitionSpec as P

        # (8,4) sharded on dim0 + (4,) replicated -> (dp, None)
        want = pops.propagate("add", [P("dp", None), P(None)], [2, 1])
        assert tuple(want) == ("dp", None)
