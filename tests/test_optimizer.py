"""Optimizer + LR scheduler + AMP tests."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def np_t(x):
    return np.asarray(x.numpy())


def quad_problem():
    # minimize ||w - 3||^2
    w = paddle.nn.ParameterList(
        [paddle.Parameter(np.zeros(4, np.float32))])
    return w


def run_opt(opt_cls, steps=60, **kw):
    w = paddle.Parameter(np.zeros(4, np.float32))
    opt = opt_cls(parameters=[w], **kw)
    for _ in range(steps):
        loss = ((w - 3.0) ** 2).sum()
        loss.backward()
        opt.step()
        opt.clear_grad()
    return np_t(w)


class TestOptimizers:
    def test_sgd(self):
        assert np.allclose(run_opt(paddle.optimizer.SGD, learning_rate=0.1),
                           3.0, atol=1e-2)

    def test_momentum(self):
        assert np.allclose(run_opt(paddle.optimizer.Momentum, steps=300,
                                   learning_rate=0.02), 3.0, atol=1e-1)

    def test_adam(self):
        assert np.allclose(run_opt(paddle.optimizer.Adam, steps=300,
                                   learning_rate=0.1), 3.0, atol=1e-1)

    def test_adamw(self):
        out = run_opt(paddle.optimizer.AdamW, steps=300, learning_rate=0.1,
                      weight_decay=0.0)
        assert np.allclose(out, 3.0, atol=1e-1)

    def test_adamw_decay(self):
        # strong decay pulls weights below the target
        out = run_opt(paddle.optimizer.AdamW, steps=300, learning_rate=0.1,
                      weight_decay=0.5)
        assert out.mean() < 3.0

    def test_others_converge(self):
        for cls, kw in [
            (paddle.optimizer.Adagrad, dict(learning_rate=0.5)),
            (paddle.optimizer.RMSProp, dict(learning_rate=0.05)),
            (paddle.optimizer.Adamax, dict(learning_rate=0.2)),
            (paddle.optimizer.Lamb, dict(learning_rate=0.05)),
        ]:
            out = run_opt(cls, steps=300, **kw)
            assert np.allclose(out, 3.0, atol=0.5), (cls.__name__, out)

    def test_adam_matches_reference_math(self):
        # one Adam step against hand computation
        w = paddle.Parameter(np.array([1.0], np.float32))
        opt = paddle.optimizer.Adam(learning_rate=0.1, parameters=[w])
        (w * 2.0).sum().backward()  # grad = 2
        opt.step()
        # m=0.2 v=0.004*... manual: m_hat=2, v_hat=4, upd = 0.1*2/(2+eps)=0.1
        assert abs(float(np_t(w)) - 0.9) < 1e-5

    def test_state_dict(self):
        w = paddle.Parameter(np.zeros(4, np.float32))
        opt = paddle.optimizer.Adam(learning_rate=0.1, parameters=[w])
        ((w - 1) ** 2).sum().backward()
        opt.step()
        sd = opt.state_dict()
        assert "accumulators" in sd and sd["accumulators"]["moment1"]

    def test_grad_clip_global_norm(self):
        w = paddle.Parameter(np.zeros(4, np.float32))
        opt = paddle.optimizer.SGD(
            learning_rate=1.0, parameters=[w],
            grad_clip=nn.ClipGradByGlobalNorm(0.1))
        ((w - 100) ** 2).sum().backward()
        opt.step()
        # update magnitude bounded by clip_norm * lr
        assert np.linalg.norm(np_t(w)) <= 0.11

    def test_multi_precision_master_weights(self):
        w = paddle.Parameter(np.zeros(4, np.float32))
        w._data = w._data.astype("bfloat16")
        opt = paddle.optimizer.AdamW(learning_rate=1e-3, parameters=[w],
                                     multi_precision=True)
        ((w.astype("float32") - 3) ** 2).sum().backward()
        opt.step()
        assert id(w) in opt._master_weights
        assert str(opt._master_weights[id(w)].dtype) == "float32"


class TestLRSchedulers:
    def test_step_decay(self):
        sch = paddle.optimizer.lr.StepDecay(0.1, step_size=2, gamma=0.5)
        lrs = []
        for _ in range(5):
            lrs.append(sch())
            sch.step()
        assert np.allclose(lrs, [0.1, 0.1, 0.05, 0.05, 0.025])

    def test_warmup_cosine(self):
        sch = paddle.optimizer.lr.CosineAnnealingWithWarmupDecay(
            max_lr=1.0, min_lr=0.1, warmup_step=10, decay_step=100)
        vals = []
        for _ in range(101):
            vals.append(sch())
            sch.step()
        assert vals[0] == 0.0 or vals[0] < 0.2
        assert abs(vals[10] - 1.0) < 0.01
        assert abs(vals[100] - 0.1) < 0.01

    def test_opt_uses_scheduler(self):
        w = paddle.Parameter(np.zeros(2, np.float32))
        sch = paddle.optimizer.lr.StepDecay(0.5, step_size=1, gamma=0.1)
        opt = paddle.optimizer.SGD(learning_rate=sch, parameters=[w])
        (w.sum()).backward()
        opt.step()
        assert np.allclose(np_t(w), -0.5)
        sch.step()
        opt.clear_grad()
        (w.sum()).backward()
        opt.step()
        assert np.allclose(np_t(w), -0.55)

    def test_linear_warmup_piecewise(self):
        sch = paddle.optimizer.lr.LinearWarmup(0.5, 4, 0.0, 0.5)
        vals = [sch() for _ in range(3) if sch.step() is None]
        sch2 = paddle.optimizer.lr.PiecewiseDecay([2, 4], [0.1, 0.2, 0.3])
        assert sch2() == 0.1


class TestAMP:
    def test_auto_cast_bf16(self):
        lin = nn.Linear(4, 4)
        x = paddle.randn([2, 4])
        with paddle.amp.auto_cast(level="O1", dtype="bfloat16"):
            y = paddle.matmul(x, lin.weight)
            assert "bfloat16" in str(y.dtype)
            z = paddle.nn.functional.softmax(y)  # black-ish: stays computed
        y2 = paddle.matmul(x, lin.weight)
        assert y2.dtype == np.float32

    def test_grad_scaler_fp16(self):
        lin = nn.Linear(4, 4)
        opt = paddle.optimizer.SGD(0.01, parameters=lin.parameters())
        scaler = paddle.amp.GradScaler(init_loss_scaling=1024.0)
        x = paddle.randn([2, 4])
        loss = lin(x).mean()
        scaled = scaler.scale(loss)
        assert abs(float(scaled.numpy()) / float(loss.numpy()) - 1024) < 1
        scaled.backward()
        scaler.step(opt)
        scaler.update()
        # grads were unscaled before step
        assert not scaler._found_inf

    def test_scaler_skips_on_inf(self):
        lin = nn.Linear(2, 2)
        w_before = np_t(lin.weight).copy()
        opt = paddle.optimizer.SGD(0.1, parameters=lin.parameters())
        scaler = paddle.amp.GradScaler(init_loss_scaling=4.0)
        lin.weight.grad = paddle.to_tensor(
            np.array([[np.inf, 0], [0, 0]], np.float32))
        lin.bias.grad = paddle.zeros([2])
        scaler.step(opt)
        assert np.allclose(np_t(lin.weight), w_before)
        assert scaler._scale < 4.0  # backed off

    def test_o2_decorate(self):
        lin = nn.Linear(4, 4)
        paddle.amp.decorate(lin, level="O2", dtype="bfloat16")
        assert "bfloat16" in str(lin.weight.dtype)
