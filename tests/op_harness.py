"""OpTest-style multi-path parity harness.

Reference analogue: /root/reference/test/legacy_test/op_test.py — OpTest
(:418) declares an op + inputs once; check_output (:2765) runs it through
every execution path (eager / static / PIR, CPU / GPU) and compares against
the numpy reference with per-dtype tolerances; check_grad (:2967) compares
numeric finite-difference gradients against the analytic ones.

TPU-native redesign: the execution paths here are the framework's real ones —
  1. eager   (op-by-op dispatch through core.dispatch.apply_op)
  2. jit     (the same paddle-level call traced under jax.jit — the
              "static graph" twin)
  3. sharded (jit with inputs device_put over the dp axis of the 8-virtual-
              device mesh — the multi-place leg; elementwise/rowwise ops
              must be sharding-invariant)
across fp32 / bf16 / fp16 with per-dtype tolerances, plus an
analytic-vs-numeric gradient check (paddle autograd tape vs central
differences on the numpy reference).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Sequence

import numpy as np

import paddle_tpu as paddle

# per-dtype (rtol, atol) — mirrors op_test.py's dtype-dependent defaults
DEFAULT_TOL = {
    "float32": (1e-5, 1e-5),
    "bfloat16": (2e-2, 2e-2),
    "float16": (2e-3, 2e-3),
}


@dataclasses.dataclass
class OpCase:
    """One op declaration (the analogue of an OpTest subclass)."""

    name: str
    fn: Callable                      # paddle-level callable on Tensors
    ref: Callable                     # numpy reference (fp32 in / out)
    inputs: Sequence[np.ndarray]      # canonical fp32 (or int) inputs
    kwargs: dict = dataclasses.field(default_factory=dict)
    dtypes: Sequence[str] = ("float32", "bfloat16", "float16")
    grad: bool = True                 # run the gradient check (fp32 only)
    grad_eps: float = 1e-3            # central-difference step
    max_relative_error: float = 5e-2  # like op_test.check_grad
    tol: dict = dataclasses.field(default_factory=dict)
    jit: bool = True                  # run the jit leg (False: ops with
                                      # data-dependent output shapes)
    sharded: bool = True              # run the dp-sharded leg
    integer_inputs: Sequence[int] = ()  # input indices never cast / diffed

    def tols(self, dtype):
        return self.tol.get(dtype, DEFAULT_TOL[dtype])


def _cast_inputs(case, dtype):
    out = []
    for i, x in enumerate(case.inputs):
        if i in case.integer_inputs or not np.issubdtype(x.dtype,
                                                         np.floating):
            out.append(x)
        else:
            import jax.numpy as jnp
            out.append(np.asarray(jnp.asarray(x).astype(dtype)))
    return out


def _to_np(out):
    import jax
    from paddle_tpu.core.tensor import Tensor
    leaves = jax.tree_util.tree_leaves(
        out, is_leaf=lambda t: isinstance(t, Tensor))
    return [np.asarray(l.numpy() if isinstance(l, Tensor) else l)
            .astype(np.float32) if np.issubdtype(
                np.asarray(l.numpy() if isinstance(l, Tensor) else l).dtype,
                np.floating) or str(getattr(
                    (l.numpy() if isinstance(l, Tensor) else l), "dtype", "")
                ) == "bfloat16"
            else np.asarray(l.numpy() if isinstance(l, Tensor) else l)
            for l in leaves]


def _run_eager(case, arrays):
    ts = [paddle.to_tensor(x) for x in arrays]
    return _to_np(case.fn(*ts, **case.kwargs))


def _run_jit(case, arrays):
    import jax
    from paddle_tpu.core.state import STATE
    from paddle_tpu.core.tensor import Tensor

    def inner(*xs):
        STATE.tracing_depth += 1
        try:
            out = case.fn(*[Tensor._wrap(x) for x in xs], **case.kwargs)
        finally:
            STATE.tracing_depth -= 1
        return jax.tree_util.tree_map(
            lambda t: t._data if isinstance(t, Tensor) else t, out,
            is_leaf=lambda t: isinstance(t, Tensor))

    import jax.numpy as jnp
    out = jax.jit(inner)(*[jnp.asarray(x) for x in arrays])
    return _to_np(jax.tree_util.tree_map(
        lambda a: paddle.to_tensor(np.asarray(a)), out))


def _run_sharded(case, arrays):
    """jit leg with batch-dim-sharded inputs over 'dp' — the multi-place
    run of op_test (same op, different placement, same numbers)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from paddle_tpu.core.state import STATE
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.distributed.env import get_mesh

    mesh = get_mesh()
    if mesh is None or mesh.shape.get("dp", 1) == 1:
        # LOCAL mesh — must not register globally (a global dp-mesh leaks
        # into later single-chip tests, which then fail batch-divisibility
        # sharding constraints; bit us in the round-5 full-suite run)
        mesh = Mesh(np.array(jax.devices()), ("dp",))
    dp = mesh.shape["dp"]
    placed = []
    for x in arrays:
        a = jnp.asarray(x)
        spec = P("dp") if (a.ndim >= 1 and a.shape[0] % dp == 0) else P()
        placed.append(jax.device_put(a, NamedSharding(mesh, spec)))

    def inner(*xs):
        STATE.tracing_depth += 1
        try:
            out = case.fn(*[Tensor._wrap(x) for x in xs], **case.kwargs)
        finally:
            STATE.tracing_depth -= 1
        return jax.tree_util.tree_map(
            lambda t: t._data if isinstance(t, Tensor) else t, out,
            is_leaf=lambda t: isinstance(t, Tensor))

    out = jax.jit(inner)(*placed)
    return _to_np(jax.tree_util.tree_map(
        lambda a: paddle.to_tensor(np.asarray(a)), out))


def _assert_close(got, want, rtol, atol, path, name):
    assert len(got) == len(want), \
        f"{name}[{path}]: {len(got)} outputs vs reference {len(want)}"
    for i, (g, w) in enumerate(zip(got, want)):
        g, w = np.asarray(g), np.asarray(w)
        assert g.shape == w.shape, \
            f"{name}[{path}] out{i}: shape {g.shape} vs ref {w.shape}"
        if np.issubdtype(w.dtype, np.floating):
            np.testing.assert_allclose(
                g.astype(np.float64), w.astype(np.float64), rtol=rtol,
                atol=atol, err_msg=f"{name}[{path}] out{i}")
        else:
            np.testing.assert_array_equal(g, w,
                                          err_msg=f"{name}[{path}] out{i}")


def check_output(case: OpCase):
    """Run every (dtype × path) combination and compare vs the numpy ref
    (op_test.py check_output :2765)."""
    ref_out = case.ref(*case.inputs, **case.kwargs)
    if not isinstance(ref_out, (tuple, list)):
        ref_out = [ref_out]
    ref_out = [np.asarray(r) for r in ref_out]
    for dtype in case.dtypes:
        arrays = _cast_inputs(case, dtype)
        rtol, atol = case.tols(dtype)
        if dtype != "float32":
            # the reference for low precision is the fp32 result
            _assert_close(_run_eager(case, arrays), ref_out, rtol, atol,
                          f"eager/{dtype}", case.name)
            continue
        _assert_close(_run_eager(case, arrays), ref_out, rtol, atol,
                      f"eager/{dtype}", case.name)
        if case.jit:
            _assert_close(_run_jit(case, arrays), ref_out, rtol, atol,
                          f"jit/{dtype}", case.name)
        if case.sharded and case.jit:
            _assert_close(_run_sharded(case, arrays), ref_out, rtol, atol,
                          f"sharded/{dtype}", case.name)


def _numeric_grad(case, arrays, wrt, cot):
    """Central differences of <ref(x), cot> w.r.t. arrays[wrt]
    (op_test.py numeric gradient :2967)."""
    x = arrays[wrt].astype(np.float64)
    eps = case.grad_eps
    g = np.zeros_like(x)
    flat = x.reshape(-1)
    gflat = g.reshape(-1)

    def val(xv):
        args = list(arrays)
        args[wrt] = xv.astype(np.float32)
        out = case.ref(*args, **case.kwargs)
        outs = out if isinstance(out, (tuple, list)) else [out]
        s = 0.0
        for o, c in zip(outs, cot):
            o = np.asarray(o, np.float64)
            if np.issubdtype(o.dtype, np.floating):
                s += float((o * c).sum())
        return s

    for i in range(flat.size):
        orig = flat[i]
        flat[i] = orig + eps
        up = val(x.reshape(arrays[wrt].shape))
        flat[i] = orig - eps
        dn = val(x.reshape(arrays[wrt].shape))
        flat[i] = orig
        gflat[i] = (up - dn) / (2 * eps)
    return g


def check_grad(case: OpCase):
    """Analytic (autograd tape) vs numeric gradients, fp32
    (op_test.py check_grad :2967 — max_relative_error criterion)."""
    if not case.grad:
        return
    arrays = [np.asarray(x) for x in case.inputs]
    diffable = [i for i, x in enumerate(arrays)
                if i not in case.integer_inputs
                and np.issubdtype(x.dtype, np.floating)]
    ts = [paddle.to_tensor(x) for x in arrays]
    for i in diffable:
        ts[i].stop_gradient = False
    out = case.fn(*ts, **case.kwargs)
    outs = out if isinstance(out, (tuple, list)) else [out]
    outs = [o for o in outs if hasattr(o, "_data")]
    # deterministic cotangent (all-ones is too symmetric for e.g. softmax)
    cot = []
    loss = None
    for o in outs:
        if "float" not in str(o.dtype):
            cot.append(None)
            continue
        rng = np.random.RandomState(7)
        c = rng.uniform(0.5, 1.5, size=tuple(o.shape)).astype(np.float64)
        cot.append(c)
        term = (o.astype("float32") * paddle.to_tensor(
            c.astype(np.float32))).sum()
        loss = term if loss is None else loss + term
    assert loss is not None, f"{case.name}: no differentiable output"
    loss.backward()
    for i in diffable:
        analytic = np.asarray(ts[i].grad.numpy(), np.float64)
        numeric = _numeric_grad(
            case, arrays, i, [c for c in cot if c is not None])
        denom = np.maximum(np.maximum(np.abs(analytic), np.abs(numeric)),
                           1e-3)
        rel = np.abs(analytic - numeric) / denom
        assert rel.max() <= case.max_relative_error, (
            f"{case.name}: grad wrt input{i} max_relative_error "
            f"{rel.max():.4f} > {case.max_relative_error} "
            f"(analytic {analytic.reshape(-1)[:4]}, "
            f"numeric {numeric.reshape(-1)[:4]})")


def run_case(case: OpCase):
    check_output(case)
    check_grad(case)
