"""Zero-bubble pipeline schedule tests.

Reference: /root/reference/python/paddle/distributed/passes/
pipeline_scheduler_pass/pipeline_zero_bubble.py — backward split into dX
(activation grad, critical path) and W (weight grad, fills the bubble)."""

import numpy as np
import pytest

import paddle_tpu as paddle


@pytest.fixture(scope="module")
def mesh_pp4():
    import jax
    if jax.device_count() < 8:
        pytest.skip("needs 8 devices")
    from paddle_tpu.distributed import fleet
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 1, "pp_degree": 4}
    hcg = fleet.init(is_collective=True, strategy=strategy)
    yield hcg
    fleet._reset()


class TestZeroBubbleTables:
    @pytest.mark.parametrize("P,M", [(2, 4), (4, 8), (4, 4), (3, 5), (8, 16)])
    def test_disjoint_complete_and_ordered(self, P, M):
        """Every (stage, mb) F, dX, W fires exactly once; a stage does at
        most one op per tick; W(m) strictly after dX(m); F/dX agree with the
        1F1B closed-form arithmetic."""
        import jax.numpy as jnp
        from paddle_tpu.distributed.pipeline import (zero_bubble_tables,
                                                     _f_sched, _b_sched)
        tb = zero_bubble_tables(P, M)
        f, b, w, T = tb["f"], tb["b"], tb["w"], tb["T"]
        for s in range(P):
            seen = {"f": {}, "b": {}, "w": {}}
            for t in range(T):
                ops = [("f", f[t, s]), ("b", b[t, s]), ("w", w[t, s])]
                active = [(k, int(m)) for k, m in ops if m >= 0]
                assert len(active) <= 1, (s, t, active)
                for k, m in active:
                    assert m not in seen[k], (s, t, k, m)
                    seen[k][m] = t
                if t < 2 * (M + P - 1):
                    mf, af = _f_sched(P, M, s, jnp.asarray(t))
                    mb_, ab = _b_sched(P, M, s, jnp.asarray(t))
                    assert int(f[t, s]) == (int(mf) if bool(af) else -1)
                    assert int(b[t, s]) == (int(mb_) if bool(ab) else -1)
            for k in seen:
                assert sorted(seen[k]) == list(range(M)), (s, k)
            for m in range(M):
                assert seen["b"][m] > seen["f"][m]
                assert seen["w"][m] > seen["b"][m]

    @pytest.mark.parametrize("P,M", [(4, 8), (4, 16), (8, 16)])
    def test_bubble_smaller_than_plain_1f1b(self, P, M):
        """Cost model: tick duration = max over stages of that tick's work,
        with F=1, dX=1, W=1 unit (backward = 2 units total).  Plain 1F1B
        does dX+dW fused in one tick (2 units); zero-bubble spreads them.
        Total schedule cost must be strictly lower."""
        from paddle_tpu.distributed.pipeline import zero_bubble_tables
        tb = zero_bubble_tables(P, M)
        f, b, w, T = tb["f"], tb["b"], tb["w"], tb["T"]
        zb_cost = 0
        for t in range(T):
            work = [
                (1 if f[t, s] >= 0 else 0)
                + (2 if b[t, s] >= 0 else 0)   # dX tick: fwd-remat + dX
                + (2 if w[t, s] >= 0 else 0)   # W tick: fwd-remat + dW
                for s in range(P)]
            zb_cost += max(work) if any(work) else 0
        plain_cost = 0
        for t in range(2 * (M + P - 1)):
            work = [(1 if f[t, s] >= 0 else 0)
                    + (3 if b[t, s] >= 0 else 0)  # fused: remat + dX + dW
                    for s in range(P)]
            plain_cost += max(work) if any(work) else 0
        assert zb_cost < plain_cost, (zb_cost, plain_cost)

    def test_ring_depth_sane(self):
        from paddle_tpu.distributed.pipeline import zero_bubble_tables
        tb = zero_bubble_tables(4, 8)
        assert tb["Q"] >= 5  # at least the 1F1B P+1
        assert tb["Q"] <= 8 + 1  # never more than M+1 slots


class TestZeroBubbleParity:
    def test_value_and_grad_matches_whole_model_pp4(self, mesh_pp4):
        """zero_bubble pipeline_value_and_grad at pp=4 == plain
        jax.value_and_grad of the composed model (grad parity incl. dW)."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.distributed.pipeline import pipeline_value_and_grad

        rng = np.random.default_rng(1)
        P_, Lpp, H = 4, 2, 8
        sp = {"w": jnp.asarray(rng.normal(size=(P_, Lpp, H, H)) * 0.3,
                               jnp.float32)}
        ex = {"emb": jnp.asarray(rng.normal(size=(16, H)), jnp.float32),
              "head": jnp.asarray(rng.normal(size=(H, 16)), jnp.float32)}
        ids = jnp.asarray(rng.integers(0, 16, size=(8, 4)), jnp.int32)
        labels = jnp.asarray(rng.integers(0, 16, size=(8, 4)), jnp.int32)

        def first_fn(e, x):
            return jnp.take(e["emb"], x, axis=0)

        def mid_fn(s, h):
            def body(hh, w):
                return jnp.tanh(hh @ w), None
            h, _ = jax.lax.scan(body, h, s["w"])
            return h

        def last_fn(e, h, lb):
            logits = h @ e["head"]
            logp = jax.nn.log_softmax(logits, -1)
            picked = jnp.take_along_axis(logp, lb[..., None], -1)[..., 0]
            return jnp.sum(-picked)

        def whole(sp_, ex_):
            h = first_fn(ex_, ids)
            for s in range(P_):
                h = mid_fn(jax.tree_util.tree_map(lambda a, _s=s: a[_s],
                                                  sp_), h)
            return last_fn(ex_, h, labels)

        ref_loss, (ref_dsp, ref_dex) = jax.value_and_grad(
            whole, argnums=(0, 1))(sp, ex)

        mesh = paddle.distributed.get_mesh()
        loss, dsp, dex = jax.jit(
            lambda s, e: pipeline_value_and_grad(
                first_fn, mid_fn, last_fn, s, e, ids, labels, 8,
                mesh=mesh, schedule="zero_bubble"))(sp, ex)

        assert np.allclose(float(loss), float(ref_loss), rtol=1e-4)
        assert np.allclose(np.asarray(dsp["w"]), np.asarray(ref_dsp["w"]),
                           atol=1e-4), \
            np.abs(np.asarray(dsp["w"]) - np.asarray(ref_dsp["w"])).max()
        for k in ex:
            assert np.allclose(np.asarray(dex[k]), np.asarray(ref_dex[k]),
                               atol=1e-4), k

    def test_gpt_zero_bubble_trains(self, mesh_pp4):
        """GPT end-to-end with schedule='zero_bubble' at pp=4 matches eager
        training loss series."""
        from paddle_tpu.distributed.engine import Pipeline1F1BTrainStep
        from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                       GPTPretrainingCriterion)

        def np_t(x):
            return np.asarray(x.numpy())

        cfg = GPTConfig(vocab_size=32, hidden_size=16, num_layers=4,
                        num_heads=2, max_seq_len=8,
                        use_flash_attention=False, dropout=0.0)
        paddle.seed(3)
        model = GPTForCausalLM(cfg)
        ref = GPTForCausalLM(cfg)
        ref.set_state_dict({k: paddle.to_tensor(np_t(v).copy())
                            for k, v in model.state_dict().items()})
        ids = paddle.randint(0, 32, [8, 8])
        lab = paddle.randint(0, 32, [8, 8])

        opt = paddle.optimizer.SGD(0.1, parameters=model.parameters())
        step = Pipeline1F1BTrainStep(model, opt, num_microbatches=8,
                                     schedule="zero_bubble")
        losses = [float(step(ids, lab).numpy()) for _ in range(3)]

        crit = GPTPretrainingCriterion()
        ropt = paddle.optimizer.SGD(0.1, parameters=ref.parameters())
        ref_losses = []
        for _ in range(3):
            loss = crit(ref(ids), lab)
            loss.backward()
            ropt.step()
            ropt.clear_grad()
            ref_losses.append(float(loss.numpy()))

        assert np.allclose(losses, ref_losses, rtol=2e-3), (
            losses, ref_losses)
        assert losses[-1] < losses[0]
