"""jit / to_static parity tests (reference pattern: test/dygraph_to_static —
run the same model eagerly and compiled, assert output parity)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def np_t(x):
    return np.asarray(x.numpy())


class TestToStatic:
    def test_function_parity(self):
        @paddle.jit.to_static
        def f(x, y):
            return paddle.tanh(x) * y + x.sum()

        x = paddle.randn([3, 3])
        y = paddle.randn([3, 3])
        expected = paddle.tanh(x) * y + x.sum()
        assert np.allclose(np_t(f(x, y)), np_t(expected), atol=1e-6)

    def test_layer_parity(self):
        net = nn.Sequential(nn.Linear(4, 16), nn.GELU(), nn.Linear(16, 2))
        x = paddle.randn([5, 4])
        eager = np_t(net(x))
        paddle.jit.to_static(net)
        static = np_t(net(x))
        assert np.allclose(eager, static, atol=1e-5)

    def test_control_flow_python(self):
        # python control flow over static shapes traces fine (SOT analogue)
        @paddle.jit.to_static
        def f(x):
            out = x
            for _ in range(3):
                out = out * 2
            if out.shape[0] > 1:
                out = out + 1
            return out

        x = paddle.ones([2, 2])
        assert np.allclose(np_t(f(x)), 9.0)

    def test_buffer_mutation_captured(self):
        bn = nn.BatchNorm1D(4)
        x = paddle.randn([16, 4])
        paddle.jit.to_static(bn)
        before = np_t(bn._mean).copy()
        bn.train()
        bn(x)
        after = np_t(bn._mean)
        assert not np.allclose(before, after)


class TestCompiledTrainStep:
    def test_loss_decreases_and_matches_eager(self):
        paddle.seed(7)
        net_e = nn.Sequential(nn.Linear(4, 16), nn.Tanh(), nn.Linear(16, 1))
        net_c = nn.Sequential(nn.Linear(4, 16), nn.Tanh(), nn.Linear(16, 1))
        net_c.set_state_dict(net_e.state_dict())
        opt_e = paddle.optimizer.SGD(0.1, parameters=net_e.parameters())
        opt_c = paddle.optimizer.SGD(0.1, parameters=net_c.parameters())
        x = paddle.randn([8, 4])
        t = paddle.randn([8, 1])

        def loss_fn(m, a, b):
            return ((m(a) - b) ** 2).mean()

        step = paddle.jit.CompiledTrainStep(net_c, loss_fn, opt_c)
        eager_losses, compiled_losses = [], []
        for _ in range(5):
            le = loss_fn(net_e, x, t)
            le.backward()
            opt_e.step()
            opt_e.clear_grad()
            eager_losses.append(float(le.numpy()))
            compiled_losses.append(float(step(x, t).numpy()))
        assert np.allclose(eager_losses, compiled_losses, atol=1e-4), (
            eager_losses, compiled_losses)
        assert compiled_losses[-1] < compiled_losses[0]

    def test_adamw_compiled(self):
        net = nn.Linear(4, 4)
        opt = paddle.optimizer.AdamW(0.01, parameters=net.parameters())
        step = paddle.jit.CompiledTrainStep(
            net, lambda m, x: (m(x) ** 2).mean(), opt)
        x = paddle.randn([4, 4])
        l0 = float(step(x).numpy())
        for _ in range(10):
            l = float(step(x).numpy())
        assert l < l0


class TestSaveLoad:
    def test_paddle_save_load(self, tmp_path):
        net = nn.Linear(3, 3)
        path = str(tmp_path / "model.pdparams")
        paddle.save(net.state_dict(), path)
        loaded = paddle.load(path)
        assert np.allclose(np_t(loaded["weight"]), np_t(net.weight))

    def test_jit_save_load(self, tmp_path):
        from paddle_tpu.static import InputSpec
        net = nn.Sequential(nn.Linear(2, 2))
        x = paddle.randn([1, 2])
        expected = np_t(net(x))
        paddle.jit.save(net, str(tmp_path / "m"),
                        input_spec=[InputSpec([1, 2], "float32")])
        net2 = paddle.jit.load(str(tmp_path / "m"))
        assert np.allclose(np_t(net2(x)), expected, atol=1e-6)

    def test_jit_save_load_fresh_process(self, tmp_path):
        """The exported artifact must run WITHOUT the original class: load
        + infer in a subprocess that never defines the model (reference:
        jit::Layer deployment contract, fluid/jit/layer.h:44)."""
        import subprocess
        import sys
        from paddle_tpu.static import InputSpec
        net = nn.Sequential(nn.Linear(4, 3), nn.ReLU(), nn.Linear(3, 2))
        x = paddle.randn([2, 4])
        expected = np_t(net(x))
        paddle.jit.save(net, str(tmp_path / "m"),
                        input_spec=[InputSpec([2, 4], "float32")])
        np.save(str(tmp_path / "x.npy"), np_t(x))
        np.save(str(tmp_path / "want.npy"), expected)
        code = f"""
import os
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import jax; jax.config.update("jax_platforms", "cpu")
import numpy as np
import paddle_tpu as paddle
m = paddle.jit.load({str(tmp_path / 'm')!r})
x = paddle.to_tensor(np.load({str(tmp_path / 'x.npy')!r}))
want = np.load({str(tmp_path / 'want.npy')!r})
got = np.asarray(m(x).numpy())
assert np.allclose(got, want, atol=1e-6), np.abs(got - want).max()
print("OK")
"""
        import os
        repo_root = os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True, timeout=240,
                           cwd=repo_root)
        assert r.returncode == 0 and "OK" in r.stdout, (r.stdout, r.stderr)

    def test_jit_save_dynamic_batch(self, tmp_path):
        """InputSpec([None, H]) exports a symbolic batch dim — the artifact
        serves any batch size."""
        from paddle_tpu.static import InputSpec
        net = nn.Sequential(nn.Linear(3, 2))
        paddle.jit.save(net, str(tmp_path / "dyn"),
                        input_spec=[InputSpec([None, 3], "float32")])
        m = paddle.jit.load(str(tmp_path / "dyn"))
        for b in (1, 4, 7):
            x = paddle.randn([b, 3])
            assert np.allclose(np_t(m(x)), np_t(net(x)), atol=1e-6)

    def test_optimizer_state_roundtrip(self, tmp_path):
        net = nn.Linear(2, 2)
        opt = paddle.optimizer.Adam(0.1, parameters=net.parameters())
        net(paddle.randn([2, 2])).sum().backward()
        opt.step()
        paddle.save(opt.state_dict(), str(tmp_path / "opt.pdopt"))
        state = paddle.load(str(tmp_path / "opt.pdopt"))
        opt2 = paddle.optimizer.Adam(0.1, parameters=net.parameters())
        opt2.set_state_dict(state)
        assert opt2._accumulators["moment1"]


class TestRecompute:
    def test_recompute_grad_parity(self):
        from paddle_tpu.distributed.fleet import recompute
        lin = nn.Linear(4, 4)
        x = paddle.randn([2, 4])
        y1 = recompute(lin, x)
        y1.sum().backward()
        g1 = np_t(lin.weight.grad)
        lin.clear_gradients()
        y2 = lin(x)
        y2.sum().backward()
        g2 = np_t(lin.weight.grad)
        assert np.allclose(np_t(y1), np_t(y2), atol=1e-6)
        assert np.allclose(g1, g2, atol=1e-5)


class TestInferencePredictor:
    def test_config_predictor_roundtrip(self, tmp_path):
        """paddle.inference Config/Predictor over a jit.save artifact
        (reference: AnalysisPredictor named-handle contract)."""
        from paddle_tpu import inference
        from paddle_tpu.static import InputSpec
        net = nn.Sequential(nn.Linear(3, 2))
        x = paddle.randn([2, 3])
        want = np_t(net(x))
        paddle.jit.save(net, str(tmp_path / "m"),
                        input_spec=[InputSpec([2, 3], "float32")])
        cfg = inference.Config(str(tmp_path / "m"))
        pred = inference.create_predictor(cfg)
        names = pred.get_input_names()
        assert names == ["input_0"]
        pred.get_input_handle(names[0]).copy_from_cpu(np_t(x))
        assert pred.run()
        out = pred.get_output_handle(
            pred.get_output_names()[0]).copy_to_cpu()
        assert np.allclose(out, want, atol=1e-6)


class TestEvalMode:
    def test_mixed_mode_restored(self):
        """eval_mode restores PER-SUBLAYER training flags — a frozen BN in
        a training model must stay frozen after jit.save/flops."""
        from paddle_tpu.jit import eval_mode
        net = nn.Sequential(nn.Linear(4, 4), nn.BatchNorm1D(4))
        net.train()
        net[1].eval()  # deliberately frozen sublayer
        with eval_mode(net):
            assert not net.training and not net[1].training
        assert net.training and net[0].training
        assert not net[1].training  # frozen stays frozen


def test_onnx_export_gated_with_alternative():
    """paddle.onnx.export mirrors the reference's delegation contract
    (python/paddle/onnx/export.py): without the onnx package it raises and
    names the StableHLO deployment path."""
    import paddle_tpu

    lin = paddle.nn.Linear(2, 2)
    with pytest.raises((RuntimeError, NotImplementedError),
                       match="jit.save"):
        paddle_tpu.onnx.export(lin, "/tmp/m", input_spec=None)
