"""paddle.distribution: the round-5 additions validated against scipy
(reference: python/paddle/distribution/ — binomial.py, cauchy.py,
multivariate_normal.py, independent.py, transformed_distribution.py)."""

import numpy as np
import pytest
import scipy.stats as st

import paddle_tpu as paddle

D = paddle.distribution


class TestNewDistributions:
    def test_multivariate_normal(self):
        paddle.seed(0)
        cov = np.asarray([[2.0, 0.5], [0.5, 1.0]], np.float32)
        mv = D.MultivariateNormal(np.zeros(2, np.float32),
                                  covariance_matrix=cov)
        pt = np.asarray([0.3, -0.7], np.float32)
        got = float(mv.log_prob(paddle.to_tensor(pt)).numpy())
        ref = st.multivariate_normal(np.zeros(2), cov).logpdf(pt)
        assert np.allclose(got, ref, atol=1e-5)
        x = np.asarray(mv.sample((20000,)).numpy())
        assert np.allclose(np.cov(x.T), cov, atol=0.1)
        ent = float(mv.entropy().numpy())
        assert np.allclose(ent, st.multivariate_normal(
            np.zeros(2), cov).entropy(), atol=1e-5)

    def test_cauchy(self):
        c = D.Cauchy(1.0, 2.0)
        for v in (-1.0, 0.0, 3.0):
            assert np.allclose(
                float(c.log_prob(paddle.to_tensor(v)).numpy()),
                st.cauchy.logpdf(v, 1.0, 2.0), atol=1e-5)
            assert np.allclose(
                float(c.cdf(paddle.to_tensor(v)).numpy()),
                st.cauchy.cdf(v, 1.0, 2.0), atol=1e-5)

    def test_binomial(self):
        b = D.Binomial(12.0, 0.4)
        for k in (0.0, 5.0, 12.0):
            assert np.allclose(
                float(b.log_prob(paddle.to_tensor(k)).numpy()),
                st.binom.logpmf(k, 12, 0.4), atol=1e-4)
        paddle.seed(3)
        x = np.asarray(b.sample((8000,)).numpy())
        assert abs(x.mean() - 4.8) < 0.15
        assert x.min() >= 0 and x.max() <= 12

    def test_independent_sums_event_dims(self):
        base = D.Normal(np.zeros((3, 4), np.float32),
                        np.ones((3, 4), np.float32))
        ind = D.Independent(base, 1)
        v = paddle.to_tensor(np.zeros((3, 4), np.float32))
        lp = np.asarray(ind.log_prob(v).numpy())
        assert lp.shape == (3,)
        assert np.allclose(lp, np.asarray(
            base.log_prob(v).numpy()).sum(-1))

    def test_transformed_lognormal(self):
        td = D.TransformedDistribution(D.Normal(0.0, 1.0),
                                       [D.ExpTransform()])
        for v in (0.5, 1.0, 2.0):
            assert np.allclose(
                float(td.log_prob(paddle.to_tensor(v)).numpy()),
                st.lognorm.logpdf(v, 1.0), atol=1e-5)
        paddle.seed(5)
        x = np.asarray(td.sample((5000,)).numpy())
        assert (x > 0).all()

    def test_affine_sigmoid_transforms_roundtrip(self):
        a = D.AffineTransform(2.0, 3.0)
        x = paddle.to_tensor(np.asarray([0.1, -1.0], np.float32))
        assert np.allclose(np.asarray(a.inverse(a.forward(x)).numpy()),
                           np.asarray(x.numpy()), atol=1e-6)
        s = D.SigmoidTransform()
        assert np.allclose(np.asarray(s.inverse(s.forward(x)).numpy()),
                           np.asarray(x.numpy()), atol=1e-5)

    def test_batch_broadcast_sampling(self):
        """Scalar loc + vector scale must give INDEPENDENT batch samples
        (round-5 review: a shared uniform gave exact 1:2:3 ratios)."""
        paddle.seed(11)
        s = np.asarray(D.Cauchy(0.0, np.asarray([1.0, 2.0, 3.0],
                                                np.float32))
                       .sample((6,)).numpy())
        assert s.shape == (6, 3)
        assert not np.allclose(s[:, 1] / s[:, 0], 2.0)
        # vector total_count with scalar probs broadcasts
        b = np.asarray(D.Binomial(np.asarray([5.0, 10.0], np.float32),
                                  0.5).sample().numpy())
        assert b.shape == (2,) and b[0] <= 5 and b[1] <= 10

    def test_transformed_eventful_base(self):
        """log-det reduces over the base's event dims (was: shape-(2,)
        output disagreeing with scipy)."""
        td = D.TransformedDistribution(
            D.MultivariateNormal(np.zeros(2, np.float32),
                                 covariance_matrix=np.eye(2,
                                                          dtype=np.float32)),
            [D.AffineTransform(0.0, 2.0)])
        lp = td.log_prob(paddle.to_tensor(np.ones(2, np.float32)))
        got = np.asarray(lp.numpy())
        assert got.shape == ()
        ref = st.multivariate_normal(np.zeros(2),
                                     np.eye(2) * 4).logpdf(np.ones(2))
        assert np.allclose(float(got), ref, atol=1e-5)

    def test_independent_rank_validated(self):
        with pytest.raises(ValueError, match="batch rank"):
            D.Independent(D.Normal(np.zeros(3, np.float32),
                                   np.ones(3, np.float32)), 2)

    def test_continuous_bernoulli_normalized(self):
        """log_prob integrates to ~1 over [0, 1]."""
        cb = D.ContinuousBernoulli(0.3)
        grid = np.linspace(1e-4, 1 - 1e-4, 2001, dtype=np.float32)
        lp = np.asarray(cb.log_prob(paddle.to_tensor(grid)).numpy())
        integral = np.trapezoid(np.exp(lp), grid)
        assert abs(integral - 1.0) < 1e-2, integral
