"""MoE top-k dispatch tests (reference: incubate/distributed/models/moe —
moe_layer.py MoELayer, gate/switch_gate.py, global_scatter_op)."""

import numpy as np
import pytest

import paddle_tpu as paddle


def naive_moe(x, gate_w, fc1_w, fc1_b, fc2_w, fc2_b, top_k):
    """Per-token loop reference (no capacity dropping)."""
    T, H = x.shape
    E = gate_w.shape[1]
    logits = x @ gate_w
    gates = np.exp(logits - logits.max(-1, keepdims=True))
    gates = gates / gates.sum(-1, keepdims=True)
    y = np.zeros_like(x)
    for t in range(T):
        order = np.argsort(-gates[t])[:top_k]
        w = gates[t][order]
        if top_k > 1:
            w = w / w.sum()
        # top-1 keeps the RAW probability (Switch Transformer semantics —
        # the normalised weight would be identically 1 with no router grad)
        for e, wi in zip(order, w):
            hdn = np.maximum(x[t] @ fc1_w[e] + fc1_b[e], 0.0)  # relu
            y[t] += wi * (hdn @ fc2_w[e] + fc2_b[e])
    return y


class TestMoeDispatch:
    def _mk(self, T=16, H=8, F=16, E=4, seed=0):
        rng = np.random.default_rng(seed)
        return (rng.normal(size=(T, H)).astype(np.float32),
                rng.normal(size=(H, E)).astype(np.float32),
                rng.normal(size=(E, H, F)).astype(np.float32) * 0.2,
                rng.normal(size=(E, F)).astype(np.float32) * 0.1,
                rng.normal(size=(E, F, H)).astype(np.float32) * 0.2,
                rng.normal(size=(E, H)).astype(np.float32) * 0.1)

    @pytest.mark.parametrize("top_k", [1, 2])
    def test_matches_naive_when_capacity_ample(self, top_k):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.incubate.moe import moe_ffn

        x, gw, w1, b1, w2, b2 = self._mk()
        # capacity_factor high enough that nothing drops
        y, aux = moe_ffn(jnp.asarray(x), jnp.asarray(gw), jnp.asarray(w1),
                         jnp.asarray(b1), jnp.asarray(w2), jnp.asarray(b2),
                         top_k=top_k, capacity_factor=4.0,
                         activation=jax.nn.relu)
        ref = naive_moe(x, gw, w1, b1, w2, b2, top_k)
        assert np.allclose(np.asarray(y), ref, atol=1e-4), \
            np.abs(np.asarray(y) - ref).max()
        assert float(aux) > 0

    def test_top1_router_gets_task_gradient(self):
        """Regression (round-4 advisor): with top_k=1 the combine weight was
        normalised to identically 1.0, so d(task_loss)/d(gate_w) was zero and
        the switch router could only learn from the aux loss.  The raw-prob
        combine weight must carry a nonzero task gradient."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.incubate.moe import moe_ffn

        x, gw, w1, b1, w2, b2 = self._mk(seed=3)

        def task_loss(gw):
            y, _aux = moe_ffn(jnp.asarray(x), gw, jnp.asarray(w1),
                              jnp.asarray(b1), jnp.asarray(w2),
                              jnp.asarray(b2), top_k=1, capacity_factor=4.0,
                              activation=jax.nn.relu)
            return jnp.sum(y ** 2)

        g = jax.grad(task_loss)(jnp.asarray(gw))
        assert float(jnp.abs(g).max()) > 1e-6, \
            "switch router receives no task-loss gradient"

    def test_compute_scales_with_top_k_not_E(self):
        """Expert tensors are [E, C, .] with E*C ~= k*T*cf — NOT [T, E, .]:
        per-token expert compute is O(top_k).  (verdict: dense-compute MoE
        ran every expert on every token.)"""
        from paddle_tpu.incubate.moe import moe_capacity
        T, E, k, cf = 1024, 8, 2, 1.25
        C = moe_capacity(T, E, k, cf)
        assert E * C <= int(k * T * cf) + E  # total slots ~ k*T*cf
        assert E * C < T * E / 2            # far below dense all-pairs

        # FLOPs check via XLA cost analysis: top-1 routing must cost well
        # under half of dense all-experts compute
        import jax
        import jax.numpy as jnp
        from paddle_tpu.incubate.moe import moe_ffn
        x, gw, w1, b1, w2, b2 = self._mk(T=256, H=64, F=256, E=8)
        args = [jnp.asarray(a) for a in (x, gw, w1, b1, w2, b2)]

        def sparse(*a):
            return moe_ffn(*a, top_k=1, capacity_factor=1.0)[0]

        def dense(x, gw, w1, b1, w2, b2):
            gates = jax.nn.softmax(x @ gw, -1)
            up = jnp.einsum("th,ehf->tef", x, w1) + b1[None]
            dn = jnp.einsum("tef,efh->teh", jax.nn.gelu(up), w2) + b2[None]
            return jnp.einsum("teh,te->th", dn, gates)

        fs = jax.jit(sparse).lower(*args).compile().cost_analysis()
        fd = jax.jit(dense).lower(*args).compile().cost_analysis()
        assert fs["flops"] < 0.5 * fd["flops"], (fs["flops"], fd["flops"])

    def test_capacity_dropping_is_clean(self):
        """Tokens over capacity produce zero output (GShard drop), never
        NaN, and dispatch stays within slots."""
        import jax.numpy as jnp
        from paddle_tpu.incubate.moe import moe_ffn

        rng = np.random.default_rng(1)
        # positive features + a one-column router => EVERY token routes to
        # expert 0 (positive logit vs 0) -> guaranteed overflow
        x = (np.abs(rng.normal(size=(32, 8))) + 0.1).astype(np.float32)
        gw = np.zeros((8, 4), np.float32)
        gw[:, 0] = 1.0
        _, _, w1, b1, w2, b2 = self._mk(T=32, H=8, F=16, E=4, seed=1)
        y, aux = moe_ffn(jnp.asarray(x), jnp.asarray(gw), jnp.asarray(w1),
                         jnp.asarray(b1), jnp.asarray(w2), jnp.asarray(b2),
                         top_k=1, capacity_factor=0.5)
        ya = np.asarray(y)
        assert np.isfinite(ya).all()
        # capacity = ceil(1*32*0.5/4) = 4 -> at most 4 tokens served
        served = (np.abs(ya).sum(-1) > 1e-7).sum()
        assert served <= 4, served

    def test_router_receives_gradient(self):
        import jax
        import jax.numpy as jnp
        from paddle_tpu.incubate.moe import moe_ffn

        x, gw, w1, b1, w2, b2 = self._mk()
        args = [jnp.asarray(a) for a in (x, gw, w1, b1, w2, b2)]

        def loss(gw):
            y, aux = moe_ffn(args[0], gw, *args[2:], top_k=2,
                             capacity_factor=2.0)
            return jnp.sum(y ** 2) + 0.01 * aux

        g = jax.grad(loss)(args[1])
        assert float(jnp.abs(g).max()) > 0


@pytest.fixture(scope="module")
def mesh_dp8():
    import jax
    if jax.device_count() < 8:
        pytest.skip("needs 8 devices")
    from paddle_tpu.distributed import fleet
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 8, "mp_degree": 1, "pp_degree": 1}
    hcg = fleet.init(is_collective=True, strategy=strategy)
    yield hcg
    fleet._reset()


class TestMoEGPT:
    def test_moe_gpt_trains_8dev(self, mesh_dp8):
        """GPT with expert-parallel MoE blocks trains (loss decreases) on an
        8-device mesh; aux loss participates in the objective."""
        from paddle_tpu.distributed import DistributedTrainStep
        from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                       GPTPretrainingCriterion)

        cfg = GPTConfig(vocab_size=64, hidden_size=16, num_layers=2,
                        num_heads=2, max_seq_len=16,
                        use_flash_attention=False, num_experts=8,
                        moe_top_k=2)
        paddle.seed(3)
        model = GPTForCausalLM(cfg)
        crit = GPTPretrainingCriterion()
        opt = paddle.optimizer.AdamW(
            1e-2, parameters=model.parameters())
        ids = paddle.randint(0, 64, [8, 16])
        lab = paddle.randint(0, 64, [8, 16])

        def loss_fn(m, x, l):
            return crit(m(x), l) + m.moe_aux_loss() * 0.01

        step = DistributedTrainStep(model, loss_fn, opt)
        losses = [float(step(ids, lab).numpy()) for _ in range(5)]
        assert all(np.isfinite(losses)), losses
        assert losses[-1] < losses[0], losses

    def test_moe_layer_api(self):
        """incubate.MoELayer standalone forward + aux_loss surface."""
        from paddle_tpu.incubate import MoELayer

        layer = MoELayer(d_model=8, d_hidden=16, num_experts=4,
                         gate="gshard")
        x = paddle.randn([2, 6, 8])
        y = layer(x)
        assert tuple(y.shape) == (2, 6, 8)
        assert layer.aux_loss is not None
        assert float(layer.aux_loss.numpy()) > 0
