"""Sharded checkpoint: dedup at save, reshard-on-load across topologies
(reference pattern: distributed/checkpoint/save_state_dict.py +
load_state_dict.py round-trip tests)."""

import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle


def np_t(x):
    return np.asarray(x.numpy())


@pytest.fixture
def clean_fleet():
    from paddle_tpu.distributed import fleet
    fleet._reset()
    yield fleet
    fleet._reset()


class TestShardedCheckpoint:
    def _init(self, fleet, **degrees):
        import jax
        need = 1
        for v in degrees.values():
            need *= v
        if jax.device_count() < need:
            pytest.skip(f"needs {need} devices")
        fleet._reset()
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = degrees
        fleet.init(is_collective=True, strategy=strategy)
        return paddle.distributed.get_mesh()

    def test_cross_topology_roundtrip(self, tmp_path, clean_fleet):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        mesh = self._init(clean_fleet, pp_degree=2, dp_degree=2, mp_degree=2)
        paddle.seed(0)
        w = paddle.randn([8, 16])
        w._data = jax.device_put(w._data, NamedSharding(mesh, P("mp", "dp")))
        b = paddle.randn([16])  # replicated
        w_np, b_np = np_t(w).copy(), np_t(b).copy()
        paddle.distributed.save_state_dict(
            {"w": w, "nested": {"b": b}, "step": 7}, str(tmp_path))

        # save on pp2×dp2×mp2  →  load on dp8 with a different partitioning
        mesh2 = self._init(clean_fleet, dp_degree=8)
        w2 = paddle.zeros([8, 16])
        w2._data = jax.device_put(w2._data, NamedSharding(mesh2, P("dp")))
        b2 = paddle.zeros([16])
        paddle.distributed.load_state_dict(
            {"w": w2, "nested": {"b": b2}}, str(tmp_path))
        assert np.allclose(np_t(w2), w_np)
        assert np.allclose(np_t(b2), b_np)
        # target sharding preserved: each device holds a [1,16] row shard
        shard = next(iter(w2._data.addressable_shards))
        assert shard.data.shape == (1, 16)

    def test_replicated_dedup_single_copy(self, tmp_path, clean_fleet):
        """A replicated tensor is written once, not once per device."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        mesh = self._init(clean_fleet, dp_degree=8)
        t = paddle.randn([4, 4])
        t._data = jax.device_put(t._data, NamedSharding(mesh, P()))
        paddle.distributed.save_state_dict({"t": t}, str(tmp_path))
        meta_file = [f for f in os.listdir(tmp_path)
                     if f.endswith("metadata.json")][0]
        with open(os.path.join(tmp_path, meta_file)) as f:
            meta = json.load(f)
        assert len(meta["tensors"]["t"]["chunks"]) == 1

    def test_shape_mismatch_raises(self, tmp_path, clean_fleet):
        t = paddle.randn([4, 4])
        paddle.distributed.save_state_dict({"t": t}, str(tmp_path))
        bad = paddle.zeros([2, 4])
        with pytest.raises(ValueError):
            paddle.distributed.load_state_dict({"t": bad}, str(tmp_path))

    def test_interrupted_resave_keeps_previous_loadable(self, tmp_path):
        """A crash mid-save (here: a stale incomplete higher save id, as a
        shrunk-world crash would leave) must not corrupt the previous
        checkpoint — load falls back to the newest COMPLETE save id.
        Regression for the round-4 advisor finding that rank 0 deleted
        old-world files with no all-ranks-committed barrier."""
        t = paddle.randn([4, 4])
        t_np = np_t(t).copy()
        paddle.distributed.save_state_dict({"t": t}, str(tmp_path))
        # simulate an interrupted save: metadata for sid=5 claims world 2
        # but only one rank's file made it to disk before the crash
        with open(os.path.join(tmp_path, "0.5.metadata.json"), "w") as f:
            json.dump({"world_size": 2, "save_id": 5,
                       "tensors": {"t": {"shape": [4, 4],
                                         "dtype": "float32",
                                         "chunks": []}}}, f)
        t2 = paddle.zeros([4, 4])
        paddle.distributed.load_state_dict({"t": t2}, str(tmp_path))
        assert np.allclose(np_t(t2), t_np)

    def test_resave_gc_and_newest_wins(self, tmp_path):
        """Repeated saves to one dir: each save gets a fresh id, load picks
        the newest, and completed older saves are garbage-collected."""
        t = paddle.randn([4, 4])
        paddle.distributed.save_state_dict({"t": t}, str(tmp_path))
        t = paddle.ones([4, 4]) * 3.0
        paddle.distributed.save_state_dict({"t": t}, str(tmp_path))
        t2 = paddle.zeros([4, 4])
        paddle.distributed.load_state_dict({"t": t2}, str(tmp_path))
        assert np.allclose(np_t(t2), 3.0)
        metas = [f for f in os.listdir(tmp_path)
                 if f.endswith("metadata.json")]
        assert len(metas) == 1, metas  # older save GC'd

    def test_async_save(self, tmp_path):
        from paddle_tpu.distributed.checkpoint import wait_async_save
        t = paddle.randn([4, 4])
        paddle.distributed.save_state_dict({"t": t}, str(tmp_path),
                                           async_save=True)
        wait_async_save()
        t2 = paddle.zeros([4, 4])
        paddle.distributed.load_state_dict({"t": t2}, str(tmp_path))
        assert np.allclose(np_t(t2), np_t(t))
