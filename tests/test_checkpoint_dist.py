"""Sharded checkpoint: dedup at save, reshard-on-load across topologies
(reference pattern: distributed/checkpoint/save_state_dict.py +
load_state_dict.py round-trip tests)."""

import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle


def np_t(x):
    return np.asarray(x.numpy())


@pytest.fixture
def clean_fleet():
    from paddle_tpu.distributed import fleet
    fleet._reset()
    yield fleet
    fleet._reset()


class TestShardedCheckpoint:
    def _init(self, fleet, **degrees):
        import jax
        need = 1
        for v in degrees.values():
            need *= v
        if jax.device_count() < need:
            pytest.skip(f"needs {need} devices")
        fleet._reset()
        strategy = fleet.DistributedStrategy()
        strategy.hybrid_configs = degrees
        fleet.init(is_collective=True, strategy=strategy)
        return paddle.distributed.get_mesh()

    def test_cross_topology_roundtrip(self, tmp_path, clean_fleet):
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        mesh = self._init(clean_fleet, pp_degree=2, dp_degree=2, mp_degree=2)
        paddle.seed(0)
        w = paddle.randn([8, 16])
        w._data = jax.device_put(w._data, NamedSharding(mesh, P("mp", "dp")))
        b = paddle.randn([16])  # replicated
        w_np, b_np = np_t(w).copy(), np_t(b).copy()
        paddle.distributed.save_state_dict(
            {"w": w, "nested": {"b": b}, "step": 7}, str(tmp_path))

        # save on pp2×dp2×mp2  →  load on dp8 with a different partitioning
        mesh2 = self._init(clean_fleet, dp_degree=8)
        w2 = paddle.zeros([8, 16])
        w2._data = jax.device_put(w2._data, NamedSharding(mesh2, P("dp")))
        b2 = paddle.zeros([16])
        paddle.distributed.load_state_dict(
            {"w": w2, "nested": {"b": b2}}, str(tmp_path))
        assert np.allclose(np_t(w2), w_np)
        assert np.allclose(np_t(b2), b_np)
        # target sharding preserved: each device holds a [1,16] row shard
        shard = next(iter(w2._data.addressable_shards))
        assert shard.data.shape == (1, 16)

    def test_replicated_dedup_single_copy(self, tmp_path, clean_fleet):
        """A replicated tensor is written once, not once per device."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P
        mesh = self._init(clean_fleet, dp_degree=8)
        t = paddle.randn([4, 4])
        t._data = jax.device_put(t._data, NamedSharding(mesh, P()))
        paddle.distributed.save_state_dict({"t": t}, str(tmp_path))
        meta_file = [f for f in os.listdir(tmp_path)
                     if f.endswith("metadata.json")][0]
        with open(os.path.join(tmp_path, meta_file)) as f:
            meta = json.load(f)
        assert len(meta["tensors"]["t"]["chunks"]) == 1

    def test_shape_mismatch_raises(self, tmp_path, clean_fleet):
        t = paddle.randn([4, 4])
        paddle.distributed.save_state_dict({"t": t}, str(tmp_path))
        bad = paddle.zeros([2, 4])
        with pytest.raises(ValueError):
            paddle.distributed.load_state_dict({"t": bad}, str(tmp_path))

    def test_interrupted_resave_keeps_previous_loadable(self, tmp_path):
        """A crash mid-save (here: a stale incomplete higher save id, as a
        shrunk-world crash would leave) must not corrupt the previous
        checkpoint — load falls back to the newest COMPLETE save id.
        Regression for the round-4 advisor finding that rank 0 deleted
        old-world files with no all-ranks-committed barrier."""
        t = paddle.randn([4, 4])
        t_np = np_t(t).copy()
        paddle.distributed.save_state_dict({"t": t}, str(tmp_path))
        # simulate an interrupted save: metadata for sid=5 claims world 2
        # but only one rank's file made it to disk before the crash
        with open(os.path.join(tmp_path, "0.5.metadata.json"), "w") as f:
            json.dump({"world_size": 2, "save_id": 5,
                       "tensors": {"t": {"shape": [4, 4],
                                         "dtype": "float32",
                                         "chunks": []}}}, f)
        t2 = paddle.zeros([4, 4])
        paddle.distributed.load_state_dict({"t": t2}, str(tmp_path))
        assert np.allclose(np_t(t2), t_np)

    def test_resave_gc_and_newest_wins(self, tmp_path):
        """Repeated saves to one dir: each save gets a fresh id, load picks
        the newest, and completed older saves are garbage-collected."""
        t = paddle.randn([4, 4])
        paddle.distributed.save_state_dict({"t": t}, str(tmp_path))
        t = paddle.ones([4, 4]) * 3.0
        paddle.distributed.save_state_dict({"t": t}, str(tmp_path))
        t2 = paddle.zeros([4, 4])
        paddle.distributed.load_state_dict({"t": t2}, str(tmp_path))
        assert np.allclose(np_t(t2), 3.0)
        metas = [f for f in os.listdir(tmp_path)
                 if f.endswith("metadata.json")]
        assert len(metas) == 1, metas  # older save GC'd

    def test_async_save(self, tmp_path):
        from paddle_tpu.distributed.checkpoint import wait_async_save
        t = paddle.randn([4, 4])
        paddle.distributed.save_state_dict({"t": t}, str(tmp_path),
                                           async_save=True)
        wait_async_save()
        t2 = paddle.zeros([4, 4])
        paddle.distributed.load_state_dict({"t": t2}, str(tmp_path))
        assert np.allclose(np_t(t2), np_t(t))


class TestMeshCheckpointManager:
    """Sharded checkpoints of a mesh-native CompiledTrainStep through
    resilience.CheckpointManager: per-shard chunked saves (replica-deduped,
    one counter-gated sync each), a manifest that records the mesh shape
    and per-leaf PartitionSpec, bit-identical same-mesh resume, resharding
    restore onto a different mesh shape, and a clear CheckpointLayoutError
    on incompatible layouts."""

    RULES = [(r"\.weight$", None)]  # placeholder; set in _make

    def _make(self, mesh):
        import paddle_tpu.jit as pjit
        import paddle_tpu.nn as nn
        from jax.sharding import PartitionSpec as P

        def mse(m, x, y):
            return ((m(x) - y) ** 2).mean()

        paddle.seed(7)
        net = nn.Sequential(nn.Linear(8, 16), nn.GELU(), nn.Linear(16, 4))
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=net.parameters())
        step = pjit.CompiledTrainStep(
            net, mse, opt, mesh=mesh,
            shard_rules=[(r"\.weight$", P(None, "mp"))])
        return step

    def _mesh(self, *shape):
        import jax
        need = int(np.prod(shape))
        if jax.device_count() < need:
            pytest.skip(f"needs {need} devices")
        from jax.sharding import Mesh
        return Mesh(np.array(jax.devices()[:need]).reshape(shape),
                    ("dp", "mp"))

    def _data(self, n=6):
        rng = np.random.RandomState(0)
        return ([rng.randn(8, 8).astype("float32") for _ in range(n)],
                [rng.randn(8, 4).astype("float32") for _ in range(n)])

    def _run(self, step, xs, ys):
        return [float(step(paddle.to_tensor(x),
                           paddle.to_tensor(y)).numpy())
                for x, y in zip(xs, ys)]

    def test_sharded_save_roundtrip_and_reshard(self, tmp_path):
        import glob
        from paddle_tpu.profiler import counters
        from paddle_tpu.resilience import CheckpointManager

        xs, ys = self._data()
        mesh_a = self._mesh(2, 2)
        step_a = self._make(mesh_a)
        self._run(step_a, xs[:3], ys[:3])
        mgr = CheckpointManager(str(tmp_path))
        before = counters.snapshot()
        mgr.save(step_a, 3)
        d = counters.delta(before)
        # the sharded save keeps the one-counter-gated-sync budget
        assert d.get("jit.syncs", 0) == 1
        assert d.get("resilience.saves", 0) == 1
        base = self._run(step_a, xs[3:], ys[3:])

        # on-disk layout: the mp-sharded (8, 16) weight was written as two
        # (8, 8) chunks (dp replicas deduped), and the manifest records
        # the mesh and the per-leaf spec for resharding restores
        meta = json.load(open(glob.glob(
            os.path.join(str(tmp_path), "step-*", "*.metadata.json"))[0]))
        w0 = meta["tensors"]["model/0.weight"]
        assert len(w0["chunks"]) == 2
        assert {tuple(c["shape"]) for c in w0["chunks"]} == {(8, 8)}
        man = json.load(open(glob.glob(
            os.path.join(str(tmp_path), "step-*", "MANIFEST.json"))[0]))
        assert man["mesh"] == {"axis_names": ["dp", "mp"],
                               "shape": [2, 2]}
        assert man["arrays"]["model/0.weight"]["spec"] == [None, "mp"]

        # same-mesh restore: bit-identical continuation
        step_a2 = self._make(mesh_a)
        info = mgr.restore(step_a2)
        assert info["step"] == 3 and not info["resharded"]
        assert self._run(step_a2, xs[3:], ys[3:]) == base

        # resharding restore onto a different mesh shape: same numbers
        # (up to fp associativity of the dp=4 gradient sum), counted
        step_b = self._make(self._mesh(4, 2))
        before = counters.snapshot()
        info_b = mgr.restore(step_b)
        d = counters.delta(before)
        assert info_b["resharded"]
        assert d.get("resilience.resharded_restores", 0) == 1
        cont = self._run(step_b, xs[3:], ys[3:])
        assert np.allclose(base, cont, rtol=1e-5, atol=1e-6)
        # the restored carry actually lives on the new 8-device mesh
        w = step_b._state[0]["0.weight"]
        assert len(w.sharding.device_set) == 8

    def test_incompatible_layout_raises(self, tmp_path):
        import paddle_tpu.jit as pjit
        import paddle_tpu.nn as nn
        from paddle_tpu.resilience import (CheckpointLayoutError,
                                           CheckpointManager)

        xs, ys = self._data(n=1)
        mesh = self._mesh(2, 2)
        step = self._make(mesh)
        self._run(step, xs, ys)
        mgr = CheckpointManager(str(tmp_path))
        mgr.save(step, 1)

        def mse(m, x, y):
            return ((m(x) - y) ** 2).mean()

        paddle.seed(7)
        net = nn.Sequential(nn.Linear(8, 32), nn.GELU(),
                            nn.Linear(32, 4))  # wrong hidden width
        opt = paddle.optimizer.AdamW(learning_rate=1e-2,
                                     parameters=net.parameters())
        bad = pjit.CompiledTrainStep(net, mse, opt, mesh=mesh)
        with pytest.raises(CheckpointLayoutError):
            mgr.restore(bad)
