"""Native C++ collation engine (io/_native/collate.cc via ctypes —
reference analogue: the C++ reader/feed path, buffered_reader.cc)."""

import numpy as np
import pytest

from paddle_tpu.io.native import (collate_stack, gather_rows,
                                  native_available)


class TestNativeCollate:
    def test_builds_and_loads(self):
        assert native_available(), \
            "g++ is in the image; the native engine must build"

    def test_stack_matches_numpy_large(self):
        rng = np.random.RandomState(0)
        items = [rng.randn(64, 1024).astype(np.float32) for _ in range(32)]
        out = collate_stack(items)
        assert out.shape == (32, 64, 1024)
        assert np.array_equal(out, np.stack(items))

    def test_stack_small_fallback(self):
        items = [np.ones((2, 2), np.float32), np.zeros((2, 2), np.float32)]
        assert np.array_equal(collate_stack(items), np.stack(items))

    def test_stack_mixed_shapes_fallback(self):
        items = [np.ones((2, 3), np.float32)] * 3
        items2 = [np.ones((3, 2), np.float32)] * 3
        assert collate_stack(items).shape == (3, 2, 3)
        assert collate_stack(items2).shape == (3, 3, 2)

    @pytest.mark.parametrize("dtype", [np.float32, np.int64, np.uint8])
    def test_dtypes(self, dtype):
        items = [np.arange(64 * 1024, dtype=dtype).reshape(64, 1024) + i
                 for i in range(20)]
        assert np.array_equal(collate_stack(items), np.stack(items))

    def test_gather_rows_matches_numpy(self):
        rng = np.random.RandomState(1)
        src = rng.randn(512, 4096).astype(np.float32)
        idx = rng.permutation(512)[:300]
        assert np.array_equal(gather_rows(src, idx), src[idx])

    def test_dataloader_uses_native_path(self):
        import paddle_tpu as paddle
        from paddle_tpu.io import DataLoader, Dataset

        class DS(Dataset):
            def __getitem__(self, i):
                return np.full((256, 1024), i, np.float32)

            def __len__(self):
                return 8

        dl = DataLoader(DS(), batch_size=8)
        (batch,) = [b for b in dl][:1]
        arr = np.asarray(batch.numpy())
        assert arr.shape == (8, 256, 1024)
        assert np.allclose(arr[3], 3.0)
