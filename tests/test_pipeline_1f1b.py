"""1F1B compiled-schedule parity tests (reference pattern:
test/auto_parallel/pipeline_scheduler_unittest.py — schedule output must
match sequential execution; fleet/meta_parallel/pipeline_parallel.py:459)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def np_t(x):
    return np.asarray(x.numpy())


@pytest.fixture(scope="module")
def mesh_pp2():
    import jax
    if jax.device_count() < 8:
        pytest.skip("needs 8 devices")
    from paddle_tpu.distributed import fleet
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 4, "mp_degree": 1, "pp_degree": 2}
    hcg = fleet.init(is_collective=True, strategy=strategy)
    yield hcg
    fleet._reset()


class TestScheduleMath:
    def test_fb_tick_disjoint_and_complete(self):
        """Every (stage, microbatch) F and B fires exactly once, F/B never
        collide on a tick, and backward of mb m on the last stage starts
        before forward of mb m+P-1 — the 1F1B property."""
        import jax.numpy as jnp
        from paddle_tpu.distributed.pipeline import _f_sched, _b_sched
        P, M = 4, 8
        T = 2 * (M + P - 1)
        for s in range(P):
            f_ticks = {}
            b_ticks = {}
            for t in range(T):
                m, act = _f_sched(P, M, s, jnp.asarray(t))
                if bool(act):
                    assert int(m) not in f_ticks
                    f_ticks[int(m)] = t
                mb, actb = _b_sched(P, M, s, jnp.asarray(t))
                if bool(actb):
                    assert int(mb) not in b_ticks
                    b_ticks[int(mb)] = t
                    # never F and B on the same tick
                    assert not bool(act)
            assert sorted(f_ticks) == list(range(M))
            assert sorted(b_ticks) == list(range(M))
            # causality: B_s(m) after F_s(m); F consumes input produced at
            # the producing stage one tick earlier
            for m in range(M):
                assert b_ticks[m] > f_ticks[m]
            # 1F1B in-flight bound PER STAGE: at most P-s+1 microbatches
            # forwarded but not yet backwarded (stage 0 is the maximum —
            # this is the memory property that distinguishes 1F1B from
            # GPipe's O(M))
            in_flight = 0
            max_in_flight = 0
            events = sorted([(t, +1) for t in f_ticks.values()]
                            + [(t, -1) for t in b_ticks.values()])
            for _, d in events:
                in_flight += d
                max_in_flight = max(max_in_flight, in_flight)
            assert max_in_flight <= P - s + 1, (s, max_in_flight)

    def test_value_and_grad_matches_whole_model(self, mesh_pp2):
        """pipeline_value_and_grad (pp=2, compiled 1F1B) == plain
        jax.value_and_grad over the composed function."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.distributed.pipeline import pipeline_value_and_grad

        rng = np.random.default_rng(0)
        P_, Lpp, H = 2, 2, 8
        sp = {"w": jnp.asarray(rng.normal(size=(P_, Lpp, H, H)) * 0.3,
                               jnp.float32)}
        ex = {"emb": jnp.asarray(rng.normal(size=(16, H)), jnp.float32),
              "head": jnp.asarray(rng.normal(size=(H, 16)), jnp.float32)}
        ids = jnp.asarray(rng.integers(0, 16, size=(8, 4)), jnp.int32)
        labels = jnp.asarray(rng.integers(0, 16, size=(8, 4)), jnp.int32)

        def first_fn(e, x):
            return jnp.take(e["emb"], x, axis=0)

        def mid_fn(s, h):
            def body(hh, w):
                return jnp.tanh(hh @ w), None
            h, _ = jax.lax.scan(body, h, s["w"])
            return h

        def last_fn(e, h, lb):
            logits = h @ e["head"]
            logp = jax.nn.log_softmax(logits, -1)
            picked = jnp.take_along_axis(
                logp, lb[..., None], -1)[..., 0]
            return jnp.sum(-picked)

        # reference: compose all stages, value_and_grad
        def whole(sp_, ex_):
            h = first_fn(ex_, ids)
            for s in range(P_):
                h = mid_fn(jax.tree_util.tree_map(lambda a, _s=s: a[_s],
                                                  sp_), h)
            return last_fn(ex_, h, labels)

        ref_loss, (ref_dsp, ref_dex) = jax.value_and_grad(
            whole, argnums=(0, 1))(sp, ex)

        mesh = paddle.distributed.get_mesh()
        loss, dsp, dex = jax.jit(
            lambda s, e: pipeline_value_and_grad(
                first_fn, mid_fn, last_fn, s, e, ids, labels, 4,
                mesh=mesh))(sp, ex)

        assert np.allclose(float(loss), float(ref_loss), rtol=1e-4)
        assert np.allclose(np.asarray(dsp["w"]), np.asarray(ref_dsp["w"]),
                           atol=1e-4)
        for k in ex:
            assert np.allclose(np.asarray(dex[k]), np.asarray(ref_dex[k]),
                               atol=1e-4), k


class TestPipeline1F1BTrainStep:
    def test_gpt_1f1b_matches_eager(self, mesh_pp2):
        """Pipeline1F1BTrainStep loss series == eager tape training with
        identical weights (reference: TestDistBase loss-series parity)."""
        from paddle_tpu.distributed.engine import Pipeline1F1BTrainStep
        from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                       GPTPretrainingCriterion)

        cfg = GPTConfig(vocab_size=32, hidden_size=16, num_layers=2,
                        num_heads=2, max_seq_len=8,
                        use_flash_attention=False, dropout=0.0)
        paddle.seed(7)
        model = GPTForCausalLM(cfg)
        ref = GPTForCausalLM(cfg)
        # deep-copy: the 1F1B step donates model buffers; aliased arrays
        # would be deleted under ref's feet
        ref.set_state_dict({k: paddle.to_tensor(np_t(v).copy())
                            for k, v in model.state_dict().items()})
        ids = paddle.randint(0, 32, [4, 8])
        lab = paddle.randint(0, 32, [4, 8])

        opt = paddle.optimizer.SGD(0.1, parameters=model.parameters())
        step = Pipeline1F1BTrainStep(model, opt, num_microbatches=4)
        losses = [float(step(ids, lab).numpy()) for _ in range(3)]

        crit = GPTPretrainingCriterion()
        ropt = paddle.optimizer.SGD(0.1, parameters=ref.parameters())
        ref_losses = []
        for _ in range(3):
            loss = crit(ref(ids), lab)
            loss.backward()
            ropt.step()
            ropt.clear_grad()
            ref_losses.append(float(loss.numpy()))

        assert np.allclose(losses, ref_losses, rtol=2e-3), (
            losses, ref_losses)
        assert losses[-1] < losses[0]

    def test_gpt_1f1b_dropout_trains_deterministically(self, mesh_pp2):
        """dropout>0 under plain 1F1B (round-4 refusal edge): per-
        (microbatch, global-layer) fold_in keys; backward replays the same
        masks, so two identical runs give identical losses and training
        converges."""
        from paddle_tpu.distributed.engine import Pipeline1F1BTrainStep
        from paddle_tpu.models import GPTConfig, GPTForCausalLM

        def run():
            cfg = GPTConfig(vocab_size=32, hidden_size=16, num_layers=2,
                            num_heads=2, max_seq_len=8,
                            use_flash_attention=False, dropout=0.2)
            paddle.seed(23)
            model = GPTForCausalLM(cfg)
            model.train()
            ids = paddle.randint(0, 32, [4, 8])
            lab = paddle.randint(0, 32, [4, 8])
            opt = paddle.optimizer.SGD(0.1, parameters=model.parameters())
            step = Pipeline1F1BTrainStep(model, opt, num_microbatches=4)
            return [float(step(ids, lab).numpy()) for _ in range(4)]

        l1 = run()
        l2 = run()
        assert all(np.isfinite(l1)), l1
        assert np.allclose(l1, l2, rtol=1e-5), (l1, l2)
        assert l1[-1] < l1[0], l1

    def test_gpt_1f1b_moe_aux_in_objective(self, mesh_pp2):
        """MoE under 1F1B: the gate loss (weighted by moe_aux_weight) is
        folded into the schedule objective instead of silently dropped —
        the same model with moe_aux_weight=0 yields a strictly different
        loss, and training decreases it."""
        from paddle_tpu.distributed.engine import Pipeline1F1BTrainStep
        from paddle_tpu.models import GPTConfig, GPTForCausalLM

        def run(aux_w, steps=5):
            cfg = GPTConfig(vocab_size=32, hidden_size=16, num_layers=2,
                            num_heads=2, max_seq_len=8, num_experts=2,
                            use_flash_attention=False, dropout=0.0,
                            moe_aux_weight=aux_w)
            paddle.seed(29)
            model = GPTForCausalLM(cfg)
            ids = paddle.randint(0, 32, [4, 8])
            lab = paddle.randint(0, 32, [4, 8])
            opt = paddle.optimizer.SGD(0.05, parameters=model.parameters())
            step = Pipeline1F1BTrainStep(model, opt, num_microbatches=4)
            return [float(step(ids, lab).numpy()) for _ in range(steps)]

        with_aux = run(1.0, steps=1)  # large weight: difference visible
        without = run(0.0, steps=1)
        assert all(np.isfinite(with_aux)), with_aux
        assert not np.allclose(with_aux[0], without[0], rtol=1e-4), \
            "aux loss had no effect on the 1F1B objective"
        # trains with a realistic weight
        losses = run(0.01)
        assert all(np.isfinite(losses)), losses
        assert losses[-1] < losses[0], losses


class TestAuxAwareSchedule:
    def test_aux_grads_match_composed_reference(self, mesh_pp2):
        """Unit-level: an aux_aware mid_fn's aux term contributes to loss
        and gradients exactly as the composed reference total
        CE + aux_scale * sum(aux over stages x microbatches)."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.distributed.pipeline import pipeline_value_and_grad

        rng = np.random.default_rng(5)
        P_, Lpp, H, M = 2, 2, 8, 4
        sp = {"w": jnp.asarray(rng.normal(size=(P_, Lpp, H, H)) * 0.3,
                               jnp.float32)}
        ex = {"emb": jnp.asarray(rng.normal(size=(16, H)), jnp.float32),
              "head": jnp.asarray(rng.normal(size=(H, 16)), jnp.float32)}
        ids = jnp.asarray(rng.integers(0, 16, size=(8, 4)), jnp.int32)
        labels = jnp.asarray(rng.integers(0, 16, size=(8, 4)), jnp.int32)
        aux_scale = 3.0

        def first_fn(e, x):
            return jnp.take(e["emb"], x, axis=0)

        def mid_fn(s, h):
            def body(hh, w):
                return jnp.tanh(hh @ w), None
            h2, _ = jax.lax.scan(body, h, s["w"])
            return h2, jnp.sum(h2.astype(jnp.float32) ** 2)

        mid_fn.aux_aware = True

        def last_fn(e, h, lb):
            logits = h @ e["head"]
            logp = jax.nn.log_softmax(logits, -1)
            return jnp.sum(-jnp.take_along_axis(
                logp, lb[..., None], -1)[..., 0])

        def whole(sp_, ex_):
            mbs = ids.reshape(M, ids.shape[0] // M, *ids.shape[1:])
            lbs = labels.reshape(M, labels.shape[0] // M, *labels.shape[1:])
            total = 0.0
            for m in range(M):
                h = first_fn(ex_, mbs[m])
                for s in range(P_):
                    h, aux = mid_fn(
                        jax.tree_util.tree_map(lambda a, _s=s: a[_s], sp_),
                        h)
                    total = total + aux * aux_scale
                total = total + last_fn(ex_, h, lbs[m])
            return total

        ref_loss, (ref_dsp, ref_dex) = jax.value_and_grad(
            whole, argnums=(0, 1))(sp, ex)

        mesh = paddle.distributed.get_mesh()
        for sched in ("1f1b", "zero_bubble"):
            loss, dsp, dex = jax.jit(
                lambda s, e, _sch=sched: pipeline_value_and_grad(
                    first_fn, mid_fn, last_fn, s, e, ids, labels, M,
                    mesh=mesh, schedule=_sch, aux_scale=aux_scale))(sp, ex)
            assert np.allclose(float(loss), float(ref_loss), rtol=1e-4), \
                (sched, float(loss), float(ref_loss))
            assert np.allclose(np.asarray(dsp["w"]),
                               np.asarray(ref_dsp["w"]), atol=1e-4), sched
            for k in ex:
                assert np.allclose(np.asarray(dex[k]),
                                   np.asarray(ref_dex[k]), atol=1e-4), \
                    (sched, k)
