"""nn breadth batch: unpool/fractional/grid_sample/rnnt/adaptive-softmax/
margin losses/beam search (reference: the per-op suites under
test/legacy_test/ for each).  torch is the oracle where it implements the
same op."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F

rng = np.random.RandomState(0)


class TestVision:
    def test_affine_grid_matches_torch(self):
        import torch
        theta = rng.rand(2, 2, 3).astype(np.float32)
        got = F.affine_grid(paddle.to_tensor(theta), [2, 3, 5, 7],
                            align_corners=True).numpy()
        want = torch.nn.functional.affine_grid(
            torch.from_numpy(theta), [2, 3, 5, 7],
            align_corners=True).numpy()
        np.testing.assert_allclose(got, want, atol=1e-5)

    @pytest.mark.parametrize("mode", ["bilinear", "nearest"])
    @pytest.mark.parametrize("align", [True, False])
    def test_grid_sample_matches_torch(self, mode, align):
        import torch
        x = rng.rand(2, 3, 6, 5).astype(np.float32)
        grid = (rng.rand(2, 4, 4, 2).astype(np.float32) * 2.2 - 1.1)
        got = F.grid_sample(paddle.to_tensor(x), paddle.to_tensor(grid),
                            mode=mode, align_corners=align).numpy()
        want = torch.nn.functional.grid_sample(
            torch.from_numpy(x), torch.from_numpy(grid), mode=mode,
            padding_mode="zeros", align_corners=align).numpy()
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_temporal_shift_shapes_and_zero_pad(self):
        x = paddle.to_tensor(rng.rand(4, 8, 3, 3).astype(np.float32))
        out = F.temporal_shift(x, seg_num=2, shift_ratio=0.25)
        assert list(out.shape) == [4, 8, 3, 3]
        # last time step's shift-back channels come from zeros
        np.testing.assert_allclose(out.numpy()[1::2][:, :2],
                                   np.zeros((2, 2, 3, 3)), atol=1e-7)


class TestUnpool:
    @pytest.mark.parametrize("spatial", [1, 2])
    def test_roundtrip_matches_torch(self, spatial):
        import torch
        if spatial == 1:
            x = rng.rand(2, 3, 8).astype(np.float32)
            out, mask = F.max_pool1d(paddle.to_tensor(x), 2, stride=2,
                                     return_mask=True)
            up = F.max_unpool1d(out, mask, 2, stride=2)
            t_out, t_idx = torch.nn.functional.max_pool1d(
                torch.from_numpy(x), 2, stride=2, return_indices=True)
            t_up = torch.nn.functional.max_unpool1d(t_out, t_idx, 2,
                                                    stride=2)
        else:
            x = rng.rand(2, 3, 8, 6).astype(np.float32)
            out, mask = F.max_pool2d(paddle.to_tensor(x), 2, stride=2,
                                     return_mask=True)
            up = F.max_unpool2d(out, mask, 2, stride=2)
            t_out, t_idx = torch.nn.functional.max_pool2d(
                torch.from_numpy(x), 2, stride=2, return_indices=True)
            t_up = torch.nn.functional.max_unpool2d(t_out, t_idx, 2,
                                                    stride=2)
        np.testing.assert_allclose(up.numpy(), t_up.numpy(), atol=1e-6)

    def test_unpool_layer(self):
        x = rng.rand(1, 2, 4, 4).astype(np.float32)
        out, mask = F.max_pool2d(paddle.to_tensor(x), 2, return_mask=True)
        up = paddle.nn.MaxUnPool2D(2)(out, mask)
        assert list(up.shape) == [1, 2, 4, 4]


class TestFractionalPool:
    def test_2d_shapes_and_coverage(self):
        x = paddle.to_tensor(rng.rand(2, 3, 9, 7).astype(np.float32))
        out = F.fractional_max_pool2d(x, output_size=(4, 3), random_u=0.3)
        assert list(out.shape) == [2, 3, 4, 3]
        # every output is a real input value and global max survives
        assert float(out.numpy().max()) == pytest.approx(
            float(x.numpy().max()))

    def test_2d_mask_roundtrip(self):
        x = paddle.to_tensor(rng.rand(1, 2, 8, 8).astype(np.float32))
        out, mask = F.fractional_max_pool2d(x, (4, 4), random_u=0.4,
                                            return_mask=True)
        flat = x.numpy().reshape(1, 2, -1)
        picked = np.take_along_axis(flat, mask.numpy().reshape(1, 2, -1),
                                    axis=2)
        np.testing.assert_allclose(picked.reshape(out.numpy().shape),
                                   out.numpy(), atol=1e-6)

    def test_3d(self):
        x = paddle.to_tensor(rng.rand(1, 2, 6, 6, 6).astype(np.float32))
        out = paddle.nn.FractionalMaxPool3D((2, 3, 2))(x)
        assert list(out.shape) == [1, 2, 2, 3, 2]


class TestLosses:
    def test_multi_margin_matches_torch(self):
        import torch
        x = rng.rand(6, 5).astype(np.float32)
        y = rng.randint(0, 5, 6)
        got = F.multi_margin_loss(paddle.to_tensor(x),
                                  paddle.to_tensor(y)).numpy()
        want = torch.nn.functional.multi_margin_loss(
            torch.from_numpy(x), torch.from_numpy(y)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_pairwise_distance_matches_torch(self):
        import torch
        a = rng.rand(4, 7).astype(np.float32)
        b = rng.rand(4, 7).astype(np.float32)
        got = F.pairwise_distance(paddle.to_tensor(a),
                                  paddle.to_tensor(b)).numpy()
        want = torch.nn.functional.pairwise_distance(
            torch.from_numpy(a), torch.from_numpy(b)).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4)

    def test_triplet_with_distance_swap(self):
        crit = paddle.nn.TripletMarginWithDistanceLoss(margin=0.5,
                                                       swap=True)
        a, p, n = (paddle.to_tensor(rng.rand(5, 4).astype(np.float32))
                   for _ in range(3))
        loss = crit(a, p, n)
        assert float(loss.numpy()) >= 0

    def test_rnnt_loss_matches_numpy_dp(self):
        """Forward-variable DP cross-check (the warprnnt ground truth)."""
        B, T, U, V = 2, 4, 3, 5
        logits = rng.rand(B, T, U + 1, V).astype(np.float32)
        labels = rng.randint(1, V, (B, U)).astype(np.int64)
        t_len = np.array([T, 3], np.int64)
        u_len = np.array([U, 2], np.int64)

        def ref_one(lg, lb, tl, ul):
            lp = lg - np.log(np.exp(lg - lg.max(-1, keepdims=True)).sum(
                -1, keepdims=True)) - lg.max(-1, keepdims=True)
            alpha = np.full((tl, ul + 1), -np.inf)
            alpha[0, 0] = 0.0
            for t in range(tl):
                for u in range(ul + 1):
                    if t == 0 and u == 0:
                        continue
                    c = []
                    if t > 0:
                        c.append(alpha[t - 1, u] + lp[t - 1, u, 0])
                    if u > 0:
                        c.append(alpha[t, u - 1] + lp[t, u - 1, lb[u - 1]])
                    alpha[t, u] = np.logaddexp.reduce(c)
            return -(alpha[tl - 1, ul] + lp[tl - 1, ul, 0])

        want = np.array([ref_one(logits[b], labels[b], t_len[b], u_len[b])
                         for b in range(B)])
        got = F.rnnt_loss(paddle.to_tensor(logits),
                          paddle.to_tensor(labels),
                          paddle.to_tensor(t_len),
                          paddle.to_tensor(u_len),
                          blank=0, reduction="none").numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4)

    def test_rnnt_loss_grad_flows(self):
        x = paddle.to_tensor(rng.rand(1, 3, 3, 4).astype(np.float32))
        x.stop_gradient = False
        loss = F.rnnt_loss(x, paddle.to_tensor(np.array([[1, 2]],
                                                        np.int64)),
                           paddle.to_tensor(np.array([3], np.int64)),
                           paddle.to_tensor(np.array([2], np.int64)))
        loss.backward()
        assert np.isfinite(x.grad.numpy()).all()

    def test_adaptive_log_softmax_matches_torch(self):
        import torch
        N, D, C = 8, 16, 20
        cutoffs = [10, 15]
        ours = paddle.nn.AdaptiveLogSoftmaxWithLoss(D, C, cutoffs,
                                                    div_value=2.0)
        theirs = torch.nn.AdaptiveLogSoftmaxWithLoss(
            D, C, cutoffs, div_value=2.0, head_bias=False)
        # copy our weights into torch (torch stores [out, in])
        with torch.no_grad():
            theirs.head.weight.copy_(torch.from_numpy(
                ours.head_weight.numpy().T))
            for i, (proj, cls_w) in enumerate(ours.tail_weights):
                theirs.tail[i][0].weight.copy_(
                    torch.from_numpy(proj.numpy().T))
                theirs.tail[i][1].weight.copy_(
                    torch.from_numpy(cls_w.numpy().T))
        x = rng.rand(N, D).astype(np.float32)
        y = rng.randint(0, C, N)
        out, loss = ours(paddle.to_tensor(x), paddle.to_tensor(y))
        t_out, t_loss = theirs(torch.from_numpy(x), torch.from_numpy(y))
        np.testing.assert_allclose(out.numpy(), t_out.detach().numpy(),
                                   atol=1e-5)
        np.testing.assert_allclose(float(loss.numpy()),
                                   float(t_loss.detach()), rtol=1e-5)

    def test_margin_cross_entropy_reduces_to_softmax_ce(self):
        """m1=1, m2=m3=0: exactly scaled softmax CE."""
        logits = (rng.rand(6, 8).astype(np.float32) * 2 - 1) * 0.9
        y = rng.randint(0, 8, 6)
        got = F.margin_cross_entropy(paddle.to_tensor(logits),
                                     paddle.to_tensor(y), margin1=1.0,
                                     margin2=0.0, margin3=0.0,
                                     scale=10.0).numpy()
        s = logits * 10.0
        lp = s - np.log(np.exp(s - s.max(1, keepdims=True)).sum(
            1, keepdims=True)) - s.max(1, keepdims=True)
        want = -lp[np.arange(6), y].mean()
        np.testing.assert_allclose(got, want, rtol=1e-5)

    def test_hsigmoid_loss_trains(self):
        head = paddle.nn.HSigmoidLoss(8, 6)
        x = paddle.to_tensor(rng.rand(10, 8).astype(np.float32))
        y = paddle.to_tensor(rng.randint(0, 6, 10).astype(np.int64))
        opt = paddle.optimizer.SGD(0.5, parameters=head.parameters())
        losses = []
        for _ in range(10):
            loss = head(x, y).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0]


class TestSequenceUtils:
    def test_sequence_mask(self):
        m = F.sequence_mask(paddle.to_tensor(np.array([1, 3], np.int64)),
                            maxlen=4)
        np.testing.assert_array_equal(m.numpy(),
                                      [[1, 0, 0, 0], [1, 1, 1, 0]])

    def test_gather_tree_walks_parents(self):
        # T=2, B=1, beam=2: step-1 beams both descend from beam 1
        ids = paddle.to_tensor(np.array(
            [[[5, 6]], [[7, 8]]], np.int64))
        parents = paddle.to_tensor(np.array(
            [[[0, 0]], [[1, 1]]], np.int64))
        full = F.gather_tree(ids, parents).numpy()
        np.testing.assert_array_equal(full[:, 0, 0], [6, 7])
        np.testing.assert_array_equal(full[:, 0, 1], [6, 8])

    def test_class_center_sample(self):
        lbl = paddle.to_tensor(np.array([2, 9, 2, 17], np.int64))
        remapped, sampled = F.class_center_sample(lbl, 20, 8)
        s = sampled.numpy()
        assert {2, 9, 17} <= set(s.tolist())
        assert len(s) == 8
        # remapped labels index into sampled
        np.testing.assert_array_equal(s[remapped.numpy()],
                                      lbl.numpy())


class TestContainersAndActivations:
    def test_layer_dict(self):
        d = paddle.nn.LayerDict({"a": paddle.nn.Linear(2, 2)})
        d["b"] = paddle.nn.ReLU()
        assert set(d.keys()) == {"a", "b"}
        assert len(d.parameters()) == 2  # from the Linear
        del d["a"]
        assert "a" not in d

    def test_softmax2d_unflatten(self):
        x = paddle.to_tensor(rng.rand(2, 3, 4, 4).astype(np.float32))
        out = paddle.nn.Softmax2D()(x)
        np.testing.assert_allclose(out.numpy().sum(1), 1.0, rtol=1e-5)
        u = paddle.nn.Unflatten(1, [3, 1])(x)
        assert list(u.shape) == [2, 3, 1, 4, 4]

    def test_inplace_activations(self):
        x = paddle.to_tensor(np.array([-1.0, 2.0], np.float32))
        F.relu_(x)
        np.testing.assert_allclose(x.numpy(), [0.0, 2.0])
        y = paddle.to_tensor(np.array([-5.0, 5.0], np.float32))
        F.hardtanh_(y)
        np.testing.assert_allclose(y.numpy(), [-1.0, 1.0])


class TestBeamSearch:
    def test_dynamic_decode_greedy_path(self):
        """Deterministic 'cell' whose logits always prefer token 2 then
        end: beam search must return that path."""
        V = 4

        class Cell:
            def __call__(self, inp, state):
                import paddle_tpu as paddle
                n = inp.shape[0]
                base = np.full((int(n), V), -5.0, np.float32)
                step = int(np.asarray(state.numpy()).reshape(-1)[0])
                if step == 0:
                    base[:, 2] = 5.0
                else:
                    base[:, 3] = 5.0   # end token
                return (paddle.to_tensor(base),
                        paddle.to_tensor(
                            np.asarray(state.numpy()) + 1))

        dec = paddle.nn.BeamSearchDecoder(
            Cell(), start_token=0, end_token=3, beam_size=2)
        init = paddle.to_tensor(np.zeros((1, 1), np.float32))
        ids, lp = paddle.nn.dynamic_decode(dec, init, max_step_num=5)
        best = ids.numpy()[0, 0]   # [B, K, T]
        assert best[0] == 2 and 3 in best.tolist()


def test_packed_flash_wrappers():
    qkv = paddle.to_tensor(rng.rand(2, 8, 3, 2, 4).astype(np.float32))
    out, _ = F.flash_attn_qkvpacked(qkv, causal=True)
    assert list(out.shape) == [2, 8, 2, 4]


def test_sparse_mask_flash_matches_dense_causal_when_start_zero():
    q = paddle.to_tensor(rng.rand(1, 6, 2, 4).astype(np.float32))
    k = paddle.to_tensor(rng.rand(1, 6, 2, 4).astype(np.float32))
    v = paddle.to_tensor(rng.rand(1, 6, 2, 4).astype(np.float32))
    starts = paddle.to_tensor(np.zeros(6, np.int32))
    got = F.flash_attention_with_sparse_mask(q, k, v, starts).numpy()
    ref, _ = F.flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(got, ref.numpy(), atol=2e-3)


def test_sparse_mask_per_batch_head_starts():
    """[B, H, S] start rows apply per batch and head (not just b0/h0)."""
    q = paddle.to_tensor(rng.rand(2, 4, 2, 4).astype(np.float32))
    starts = np.zeros((2, 2, 4), np.int32)
    starts[1, 1, :] = 2   # batch 1 head 1: rows attend only from key 2 on
    got = F.flash_attention_with_sparse_mask(
        q, q, q, paddle.to_tensor(starts)).numpy()
    ref, _ = F.flash_attention(q, q, q, causal=True)
    # batch 0 matches dense causal; batch 1 head 1 differs
    np.testing.assert_allclose(got[0], ref.numpy()[0], atol=2e-3)
    assert not np.allclose(got[1, :, 1], ref.numpy()[1, :, 1], atol=1e-4)


def test_pool_mask_nhwc_and_asymmetric_padding():
    import torch
    x = rng.rand(1, 3, 6, 6).astype(np.float32)
    # NHWC mask must equal the NCHW mask transposed
    out_c, m_c = F.max_pool2d(paddle.to_tensor(x), 2, stride=2,
                              return_mask=True)
    x_hwc = np.transpose(x, (0, 2, 3, 1))
    out_h, m_h = F.max_pool2d(paddle.to_tensor(x_hwc), 2, stride=2,
                              return_mask=True, data_format="NHWC")
    np.testing.assert_array_equal(
        np.transpose(m_h.numpy(), (0, 3, 1, 2)), m_c.numpy())
    # pair-form padding works and matches torch's symmetric case
    out_p, m_p = F.max_pool2d(paddle.to_tensor(x), 2, stride=2,
                              padding=[[1, 1], [1, 1]], return_mask=True)
    t_out, t_idx = torch.nn.functional.max_pool2d(
        torch.from_numpy(x), 2, stride=2, padding=1, return_indices=True)
    np.testing.assert_array_equal(m_p.numpy(), t_idx.numpy())


def test_fractional_kernel_size_rejected():
    x = paddle.to_tensor(rng.rand(1, 2, 8, 8).astype(np.float32))
    with pytest.raises(NotImplementedError, match="kernel_size"):
        F.fractional_max_pool2d(x, (4, 4), kernel_size=3)


def test_rnnt_fastemit_scales_label_grads_only():
    """FastEmit leaves the loss value unchanged but scales label-emission
    gradients by (1+lambda)."""
    logits = rng.rand(1, 3, 3, 4).astype(np.float32)
    args = (paddle.to_tensor(np.array([[1, 2]], np.int64)),
            paddle.to_tensor(np.array([3], np.int64)),
            paddle.to_tensor(np.array([2], np.int64)))
    x0 = paddle.to_tensor(logits)
    l0 = F.rnnt_loss(x0, *args, fastemit_lambda=0.0)
    x1 = paddle.to_tensor(logits)
    l1 = F.rnnt_loss(x1, *args, fastemit_lambda=0.5)
    np.testing.assert_allclose(float(l0.numpy()), float(l1.numpy()),
                               rtol=1e-6)
    x0.stop_gradient = False
    F.rnnt_loss(x0, *args, fastemit_lambda=0.0).backward()
    x1.stop_gradient = False
    F.rnnt_loss(x1, *args, fastemit_lambda=0.5).backward()
    assert not np.allclose(x0.grad.numpy(), x1.grad.numpy(), atol=1e-7)


def test_varlen_qkvpacked_default_scale_is_rsqrt_d():
    qkv = rng.rand(10, 3, 2, 16).astype(np.float32)
    cu = np.array([0, 4, 10], np.int32)
    out_default, _ = F.flash_attn_varlen_qkvpacked(
        paddle.to_tensor(qkv), paddle.to_tensor(cu), paddle.to_tensor(cu),
        4, 6)
    out_explicit, _ = F.flash_attn_varlen_qkvpacked(
        paddle.to_tensor(qkv), paddle.to_tensor(cu), paddle.to_tensor(cu),
        4, 6, scale=0.25)
    np.testing.assert_allclose(out_default.numpy(), out_explicit.numpy(),
                               atol=1e-6)


class TestCeilMode:
    """ceil_mode was silently ignored in _pool (pre-existing); torch is the
    oracle for all three fixed paths."""

    def test_max_pool1d(self):
        import torch
        x = rng.rand(1, 1, 5).astype(np.float32)
        got = F.max_pool1d(paddle.to_tensor(x), 2, stride=2,
                           ceil_mode=True)
        want = torch.nn.functional.max_pool1d(
            torch.from_numpy(x), 2, stride=2, ceil_mode=True)
        np.testing.assert_allclose(got.numpy(), want.numpy(), atol=1e-6)

    def test_avg_pool2d_padded_exclusive(self):
        import torch
        x = rng.rand(1, 2, 7, 7).astype(np.float32)
        got = F.avg_pool2d(paddle.to_tensor(x), 3, stride=2, padding=1,
                           ceil_mode=True, exclusive=True)
        want = torch.nn.functional.avg_pool2d(
            torch.from_numpy(x), 3, stride=2, padding=1, ceil_mode=True,
            count_include_pad=False)
        np.testing.assert_allclose(got.numpy(), want.numpy(), atol=1e-5)


def test_grid_sample_reflection_rejected():
    x = paddle.to_tensor(rng.rand(1, 1, 4, 4).astype(np.float32))
    g = paddle.to_tensor(np.zeros((1, 2, 2, 2), np.float32))
    with pytest.raises(NotImplementedError, match="reflection"):
        F.grid_sample(x, g, padding_mode="reflection")


def test_fractional_pool_random_u_varies_per_call():
    x = paddle.to_tensor(rng.rand(1, 2, 9, 9).astype(np.float32))
    outs = {F.fractional_max_pool2d(x, 4).numpy().tobytes()
            for _ in range(6)}
    assert len(outs) > 1  # stochastic regions, not a fixed seed
