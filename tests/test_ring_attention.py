"""Ring attention (context parallel over 'sep') parity tests.

Reference capability: segment-parallel sequence scaling
(fleet/base/topology.py:240, meta_parallel/segment_parallel.py); SURVEY §5
long-context requirement."""

import numpy as np
import pytest

import paddle_tpu as paddle


@pytest.fixture(scope="module")
def mesh_sep4():
    import jax
    if jax.device_count() < 8:
        pytest.skip("needs 8 devices")
    from paddle_tpu.distributed import fleet
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 1,
                               "pp_degree": 1, "sep_degree": 4}
    hcg = fleet.init(is_collective=True, strategy=strategy)
    yield hcg
    fleet._reset()


class TestRingAttention:
    @pytest.mark.parametrize("causal", [True, False])
    def test_matches_single_device(self, mesh_sep4, causal):
        """sep=4 ring attention == single-device reference attention,
        forward and gradients."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.distributed import get_mesh
        from paddle_tpu.kernels.flash_attention import reference_attention
        from paddle_tpu.kernels.ring_attention import ring_attention

        rng = np.random.default_rng(0)
        B, S, H, D = 2, 32, 2, 8
        q = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
        mesh = get_mesh()

        def ring_loss(q, k, v):
            o = ring_attention(q, k, v, causal=causal, mesh=mesh)
            return jnp.sum(o.astype(jnp.float32) ** 2), o

        def ref_loss(q, k, v):
            o = reference_attention(q, k, v, causal=causal)
            return jnp.sum(o.astype(jnp.float32) ** 2), o

        with mesh:
            (l1, o1), g1 = jax.jit(jax.value_and_grad(
                ring_loss, argnums=(0, 1, 2), has_aux=True))(q, k, v)
        (l2, o2), g2 = jax.value_and_grad(
            ref_loss, argnums=(0, 1, 2), has_aux=True)(q, k, v)

        assert np.allclose(np.asarray(o1), np.asarray(o2), atol=2e-5), \
            np.abs(np.asarray(o1) - np.asarray(o2)).max()
        assert np.allclose(float(l1), float(l2), rtol=1e-5)
        for a, b, n in zip(g1, g2, "qkv"):
            assert np.allclose(np.asarray(a), np.asarray(b), atol=2e-4), \
                (n, np.abs(np.asarray(a) - np.asarray(b)).max())

    @pytest.mark.parametrize("causal", [True, False])
    def test_pallas_chunk_path_with_grads(self, mesh_sep4, causal):
        """S=1024/sep=4 -> 256-token chunks (%128==0): the Pallas _flash_fwd
        path actually runs inside the shard_map ring (interpret mode), and
        gradients flow through flash_attention_with_lse's custom VJP —
        regression for the round-4 advisor finding that raw _flash_fwd had no
        VJP and jax.grad crashed on exactly this path."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.distributed import get_mesh
        from paddle_tpu.kernels import flash_attention as fa
        from paddle_tpu.kernels.flash_attention import reference_attention
        from paddle_tpu.kernels.ring_attention import ring_attention

        rng = np.random.default_rng(3)
        B, S, H, D = 1, 1024, 2, 64
        q = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
        k = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
        v = jnp.asarray(rng.normal(size=(B, S, H, D)), jnp.float32)
        mesh = get_mesh()

        def ring_loss(q, k, v):
            o = ring_attention(q, k, v, causal=causal, mesh=mesh)
            return jnp.sum(o.astype(jnp.float32) ** 2)

        def ref_loss(q, k, v):
            o = reference_attention(q, k, v, causal=causal)
            return jnp.sum(o.astype(jnp.float32) ** 2)

        fa._INTERPRET[0] = True
        try:
            with mesh:
                l1, g1 = jax.jit(jax.value_and_grad(
                    ring_loss, argnums=(0, 1, 2)))(q, k, v)
        finally:
            fa._INTERPRET[0] = False
        l2, g2 = jax.value_and_grad(ref_loss, argnums=(0, 1, 2))(q, k, v)

        assert np.allclose(float(l1), float(l2), rtol=1e-4)
        for a, b, n in zip(g1, g2, "qkv"):
            assert np.allclose(np.asarray(a), np.asarray(b), atol=5e-2), \
                (n, np.abs(np.asarray(a) - np.asarray(b)).max())

    def test_gpt_context_parallel_trains(self, mesh_sep4):
        """GPT with context_parallel=True trains on a sep=4 mesh."""
        from paddle_tpu.distributed import DistributedTrainStep
        from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                       GPTPretrainingCriterion)

        cfg = GPTConfig(vocab_size=64, hidden_size=16, num_layers=2,
                        num_heads=2, max_seq_len=32,
                        use_flash_attention=False, context_parallel=True,
                        sequence_parallel=False)
        paddle.seed(5)
        model = GPTForCausalLM(cfg)
        crit = GPTPretrainingCriterion()
        opt = paddle.optimizer.AdamW(1e-2, parameters=model.parameters())
        ids = paddle.randint(0, 64, [4, 32])
        lab = paddle.randint(0, 64, [4, 32])

        def loss_fn(m, x, l):
            return crit(m(x), l)

        step = DistributedTrainStep(model, loss_fn, opt)
        losses = [float(step(ids, lab).numpy()) for _ in range(4)]
        assert all(np.isfinite(losses)), losses
        assert losses[-1] < losses[0], losses
