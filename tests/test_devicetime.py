"""Device-time & efficiency plane (``profiler.devicetime``): ledger math
(MFU / roofline joins and their edge cases), sampling economics (OFF is
free, ON pays exactly the budgeted fences, thread-safe arming), the
watchdogs, and the ``/programs`` + ``POST /profile`` ops endpoints."""

import json
import threading
import urllib.request
from urllib.error import HTTPError

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import flags as core_flags
from paddle_tpu.profiler import counters, devicetime, health, metrics
from paddle_tpu.profiler.ops import OpsServer

MiB = 1024 * 1024


@pytest.fixture(autouse=True)
def _dt_isolation():
    saved = {k: core_flags.flag(k) for k in
             ("FLAGS_device_time_sample", "FLAGS_peak_tflops",
              "FLAGS_peak_hbm_gbps", "FLAGS_device_telemetry")}
    devicetime.reset()
    yield
    core_flags.set_flags(saved)
    devicetime.reset()


def _seed_stats(name, **fields):
    """Stand in for capture_program_stats: plant AOT FLOPs/HBM bytes."""
    metrics.record_program(name, **fields)


# -- ledger math -------------------------------------------------------------
class TestLedgerMath:
    def test_mfu_and_compute_bound_roofline(self):
        core_flags.set_flags({"FLAGS_peak_tflops": 197.0,
                              "FLAGS_peak_hbm_gbps": 819.0})
        _seed_stats("dtm.matmul", flops=2e9, arg_bytes=MiB, out_bytes=MiB)
        devicetime._record_sample("dtm.matmul", 1e-3)   # 1ms sample
        row = devicetime.snapshot()["programs"][0]
        assert row["name"] == "dtm.matmul"
        assert row["tflops"] == pytest.approx(2.0, rel=1e-6)
        assert row["mfu"] == pytest.approx(2.0 / 197.0, rel=1e-6)
        assert row["hbm_gbps"] == pytest.approx(2 * MiB / 1e-3 / 1e9)
        # AI ~953 FLOP/B >> balance 197e12/819e9 ~240 FLOP/B
        assert row["ai"] == pytest.approx(2e9 / (2 * MiB))
        assert row["roofline"] == "compute-bound"
        # gauges republished per sample
        st = metrics.program_stats("dtm.matmul")
        assert st["mfu"] == pytest.approx(2.0 / 197.0, rel=1e-6)
        assert st["device_time_mean_ms"] == pytest.approx(1.0)

    def test_zero_flop_copy_is_bandwidth_bound(self):
        core_flags.set_flags({"FLAGS_peak_tflops": 197.0,
                              "FLAGS_peak_hbm_gbps": 819.0})
        _seed_stats("dtm.copy", arg_bytes=4 * MiB, out_bytes=4 * MiB)
        devicetime._record_sample("dtm.copy", 1e-3)
        row = devicetime.snapshot()["programs"][0]
        assert row["tflops"] is None and row["mfu"] is None
        assert row["hbm_gbps"] == pytest.approx(8 * MiB / 1e-3 / 1e9)
        assert row["roofline"] == "bandwidth-bound"

    def test_missing_peak_flags_degrade_to_unknown(self):
        core_flags.set_flags({"FLAGS_peak_tflops": 0.0,
                              "FLAGS_peak_hbm_gbps": 0.0})
        _seed_stats("dtm.nopeak", flops=2e9, arg_bytes=MiB, out_bytes=MiB)
        devicetime._record_sample("dtm.nopeak", 1e-3)
        row = devicetime.snapshot()["programs"][0]
        assert row["tflops"] == pytest.approx(2.0, rel=1e-6)  # flag-free
        assert row["mfu"] is None
        assert row["roofline"] == "unknown"

    def test_no_aot_stats_degrades_field_by_field(self):
        devicetime._record_sample("dtm.uncaptured", 1e-3)
        row = devicetime.snapshot()["programs"][0]
        assert row["mean_ms"] == pytest.approx(1.0)
        for k in ("tflops", "mfu", "hbm_gbps", "ai"):
            assert row[k] is None
        assert row["roofline"] == "unknown"

    def test_int8_decorated_program_name_joins(self):
        core_flags.set_flags({"FLAGS_peak_tflops": 197.0,
                              "FLAGS_peak_hbm_gbps": 819.0})
        name = "serving.decode_paged@off:int8"   # _prog_key-decorated
        _seed_stats(name, flops=1e9, arg_bytes=MiB, out_bytes=MiB)
        devicetime._record_sample(name, 1e-3)
        row = devicetime.snapshot()["programs"][0]
        assert row["name"] == name
        assert row["mfu"] is not None

    def test_share_and_est_total(self):
        devicetime._record_sample("dtm.a", 3e-3)
        devicetime._record_sample("dtm.b", 1e-3)
        snap = devicetime.snapshot()
        assert snap["est_total_s"] == pytest.approx(4e-3)
        by = {r["name"]: r for r in snap["programs"]}
        assert by["dtm.a"]["share"] == pytest.approx(0.75)
        assert snap["programs"][0]["name"] == "dtm.a"   # sorted by time

    def test_regression_ratio_trailing_vs_baseline(self):
        for _ in range(8):
            devicetime._record_sample("dtm.reg", 1e-3)
        for _ in range(8):
            devicetime._record_sample("dtm.reg", 4e-3)
        row = devicetime.snapshot()["programs"][0]
        assert row["regression"] == pytest.approx(4.0, rel=1e-6)

    def test_summary_table_renders(self):
        assert "no device-time samples" in devicetime.summary()
        _seed_stats("dtm.tab", flops=2e9, arg_bytes=MiB, out_bytes=MiB)
        devicetime._record_sample("dtm.tab", 1e-3)
        txt = devicetime.summary()
        assert "dtm.tab" in txt and "MFU" in txt and "Bound" in txt

    def test_bench_block_shape(self):
        devicetime._record_sample("dtm.blk", 2e-3)
        blk = devicetime.bench_block()
        assert blk["programs"]["dtm.blk"]["share"] == pytest.approx(1.0)
        assert blk["programs"]["dtm.blk"]["mean_ms"] == pytest.approx(2.0)


# -- sampling economics ------------------------------------------------------
class TestSampling:
    def test_off_is_zero_movement(self):
        before = counters.snapshot()
        for _ in range(16):
            assert devicetime.note("dts.off") is None
        d = counters.delta(before)
        assert not [k for k in d if k.startswith(("jit.devicetime.",
                                                  "program."))]
        assert devicetime.snapshot()["programs"] == []
        assert not devicetime.enabled()

    def test_every_nth_exact_budget(self):
        core_flags.set_flags({"FLAGS_device_time_sample": 4})
        devicetime.reset()
        before = counters.snapshot()
        tokens = [devicetime.note("dts.n4") for _ in range(8)]
        armed = [t for t in tokens if t is not None]
        assert len(armed) == 2                 # seq 0 and 4
        for t in armed:
            assert devicetime.observe(t) is not None
        d = counters.delta(before)
        assert d["jit.devicetime.dispatches"] == 8
        assert d["jit.devicetime.sampled_syncs"] == 2
        row = devicetime.snapshot()["programs"][0]
        assert (row["dispatches"], row["sampled"]) == (8, 2)

    def test_observe_none_token_is_noop(self):
        assert devicetime.observe(None) is None

    def test_thread_safe_exact_ceil(self):
        core_flags.set_flags({"FLAGS_device_time_sample": 2})
        devicetime.reset()
        before = counters.snapshot()

        def pump(i):
            for _ in range(25):
                devicetime.observe(devicetime.note(f"dts.t{i}"))

        threads = [threading.Thread(target=pump, args=(i,))
                   for i in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        d = counters.delta(before)
        assert d["jit.devicetime.dispatches"] == 100
        assert d["jit.devicetime.sampled_syncs"] == 50   # ceil(100/2)

    def test_flag_off_keeps_ledger_until_reset(self):
        core_flags.set_flags({"FLAGS_device_time_sample": 1})
        devicetime.observe(devicetime.note("dts.keep"))
        core_flags.set_flags({"FLAGS_device_time_sample": 0})
        assert devicetime.snapshot()["programs"]   # observer never resets
        devicetime.reset()
        assert devicetime.snapshot()["programs"] == []


# -- real engine: identity + budget under sampling ---------------------------
class TestEngineSampling:
    def test_paged_engine_identity_and_budget(self):
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        from paddle_tpu.serving import LLMEngine
        paddle.seed(31)
        model = GPTForCausalLM(GPTConfig(
            vocab_size=64, hidden_size=32, num_layers=1, num_heads=4,
            max_seq_len=32, use_flash_attention=False))
        model.eval()
        rng = np.random.RandomState(3)
        prompts = [rng.randint(0, 64, size=n).tolist() for n in (5, 9)]

        def engine():
            return LLMEngine(model, max_slots=2, max_seq_len=32,
                             min_bucket=4, kv_layout="paged",
                             block_size=4, prefill_chunk=8)

        def run(eng):
            hs = [eng.add_request(p, max_new_tokens=3) for p in prompts]
            while not all(h.is_finished for h in hs):
                eng.step()
            return [list(h.tokens) for h in hs]

        base_eng = engine()
        run(base_eng)                       # warm: compiles
        base = run(base_eng)                # reference tokens

        eng = engine()
        run(eng)                            # warm (sampling still off)
        core_flags.set_flags({"FLAGS_device_time_sample": 2})
        devicetime.reset()
        before = counters.snapshot()
        on = run(eng)
        d = counters.delta(before)
        core_flags.set_flags({"FLAGS_device_time_sample": 0})
        assert on == base                   # token identity under fences
        disp = d.get("jit.devicetime.dispatches", 0)
        assert disp > 0
        assert d.get("jit.devicetime.sampled_syncs", 0) == -(-disp // 2)
        assert not d.get("serving.retraces", 0)
        names = {r["name"] for r in devicetime.snapshot()["programs"]}
        assert "serving.decode_paged" in names


# -- watchdogs ---------------------------------------------------------------
class TestWatchdogs:
    def _mon(self, name):
        wd = [w for w in health.default_watchdogs() if w.name == name][0]
        return health.HealthMonitor(rules=[wd])

    def test_mfu_collapse_fires_then_resolves(self):
        core_flags.set_flags({"FLAGS_peak_tflops": 197.0,
                              "FLAGS_peak_hbm_gbps": 819.0})
        mon = self._mon("mfu_collapse")
        mon.tick(now=0.0)
        mon.tick(now=1.0)
        assert mon.firing() == []           # no sampling activity: gated
        # dominant program at ~1% MFU with enough samples
        _seed_stats("dtw.slow", flops=2e9, arg_bytes=MiB, out_bytes=MiB)
        for _ in range(4):
            devicetime._record_sample("dtw.slow", 1e-3)   # 2 TFLOP/s
        mon.tick(now=2.0)
        firing = mon.firing()
        assert [a.name for a in firing] == ["mfu_collapse"]
        assert firing[0].detail["program"] == "dtw.slow"
        # once the sampled window ages past the 15s watchdog span the
        # sampling-activity gate closes and the alert resolves
        mon.tick(now=18.0)
        assert mon.firing() == []

    def test_device_time_regression_fires(self):
        mon = self._mon("device_time_regression")
        mon.tick(now=0.0)
        for _ in range(8):
            devicetime._record_sample("dtw.reg", 1e-3)
        for _ in range(8):
            devicetime._record_sample("dtw.reg", 3e-3)   # 3x baseline
        mon.tick(now=1.0)
        firing = mon.firing()
        assert [a.name for a in firing] == ["device_time_regression"]
        assert firing[0].detail["regression"] == pytest.approx(3.0,
                                                               rel=1e-6)


# -- ops endpoints -----------------------------------------------------------
class TestEndpoints:
    def test_programs_endpoint(self):
        core_flags.set_flags({"FLAGS_peak_tflops": 197.0,
                              "FLAGS_peak_hbm_gbps": 819.0})
        _seed_stats("dte.prog", flops=2e9, arg_bytes=MiB, out_bytes=MiB)
        devicetime._record_sample("dte.prog", 1e-3)
        with OpsServer() as srv:
            with urllib.request.urlopen(srv.url("/programs"),
                                        timeout=10) as r:
                obj = json.loads(r.read())
        names = [p["name"] for p in obj["programs"]]
        assert "dte.prog" in names
        row = obj["programs"][names.index("dte.prog")]
        assert row["mfu"] is not None and row["roofline"] == "compute-bound"
        assert obj["program_stats"]["dte.prog"]["flops"] == 2e9

    def test_profile_endpoint_capture_and_single_flight(self, monkeypatch):
        calls = []
        started = threading.Event()
        release = threading.Event()

        def fake_start(path):
            calls.append(("start", path))
            started.set()

        def fake_stop():
            calls.append(("stop",))

        import time as _time
        import types
        monkeypatch.setattr(devicetime, "_start_trace", fake_start)
        monkeypatch.setattr(devicetime, "_stop_trace", fake_stop)
        # swap the module's time handle so only capture_profile's sleep
        # blocks on our event (the global time module stays untouched)
        monkeypatch.setattr(devicetime, "time", types.SimpleNamespace(
            sleep=lambda s: release.wait(timeout=5.0),
            perf_counter=_time.perf_counter))
        with OpsServer() as srv:
            # a long capture in flight ...
            def long_capture():
                devicetime.capture_profile(400)

            t = threading.Thread(target=long_capture)
            t.start()
            assert started.wait(timeout=5.0)
            # ... makes a concurrent POST bounce with 409
            with pytest.raises(HTTPError) as ei:
                urllib.request.urlopen(srv.url("/profile?ms=5"), data=b"",
                                       timeout=10)
            assert ei.value.code == 409
            release.set()
            t.join(timeout=5.0)
            # and once free, the POST succeeds and returns the dump path
            with urllib.request.urlopen(srv.url("/profile?ms=5"),
                                        data=b"", timeout=10) as r:
                obj = json.loads(r.read())
        assert obj["ms"] == 5 and "ptpu-profile-" in obj["path"]
        assert calls[0][0] == "start" and ("stop",) in calls

    def test_profile_bad_ms_is_400(self):
        with OpsServer() as srv:
            for q in ("ms=abc", "ms=0", "ms=-3"):
                with pytest.raises(HTTPError) as ei:
                    urllib.request.urlopen(srv.url(f"/profile?{q}"),
                                           data=b"", timeout=10)
                assert ei.value.code == 400

    def test_capture_profile_clamps_to_max(self, monkeypatch):
        monkeypatch.setattr(devicetime, "_start_trace", lambda p: None)
        monkeypatch.setattr(devicetime, "_stop_trace", lambda: None)
        out = devicetime.capture_profile(10_000_000, max_ms=50)
        assert out["ms"] == 50

    def test_capture_profile_busy_raises(self, monkeypatch):
        monkeypatch.setattr(devicetime, "_start_trace", lambda p: None)
        monkeypatch.setattr(devicetime, "_stop_trace", lambda: None)
        assert devicetime._PROFILE_LOCK.acquire(blocking=False)
        try:
            with pytest.raises(devicetime.ProfileBusy):
                devicetime.capture_profile(5)
        finally:
            devicetime._PROFILE_LOCK.release()
