"""Parameter-server stack (L11) tests.

Reference analogue: test/legacy_test/test_dist_fleet_ps*.py — PS training
with sparse_embedding tables and geo/a_sync strategies.  Here servers run
in-process (threaded rpc loop) so the full pull/train/push cycle is
exercised without process orchestration.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.ps import (GeoTrainer, ParameterServer, PSClient,
                                       SparseEmbedding)


@pytest.fixture
def server():
    s = ParameterServer(port=0).start()
    yield s
    s.stop()


@pytest.fixture
def two_servers():
    ss = [ParameterServer(port=0).start() for _ in range(2)]
    yield ss
    for s in ss:
        s.stop()


def test_sparse_pull_push_roundtrip(server):
    c = PSClient([server.endpoint])
    c.create_sparse_table("emb", 4, initializer="zeros")
    ids = np.array([3, 7, 3])
    vals = c.pull_sparse("emb", ids)
    assert vals.shape == (3, 4)
    np.testing.assert_array_equal(vals, 0)
    # push grad 1.0 on id 3 twice and id 7 once, lr=0.1 (sgd apply-on-push)
    c.push_sparse("emb", ids, np.ones((3, 4), np.float32), lr=0.1)
    after = c.pull_sparse("emb", np.array([3, 7]))
    np.testing.assert_allclose(after[0], -0.2, rtol=1e-6)  # 2 grads summed
    np.testing.assert_allclose(after[1], -0.1, rtol=1e-6)
    c.close()


def test_dense_grad_and_delta(server):
    c = PSClient([server.endpoint])
    c.create_dense_table("w", (2, 3))
    np.testing.assert_array_equal(c.pull_dense("w"), 0)
    c.push_dense_grad("w", np.ones((2, 3), np.float32), lr=0.5)
    np.testing.assert_allclose(c.pull_dense("w"), -0.5)
    c.push_dense_delta("w", np.full((2, 3), 0.5, np.float32))
    np.testing.assert_allclose(c.pull_dense("w"), 0.0)
    c.close()


def test_sharded_sparse_routing(two_servers):
    """ids shard by id % num_servers; every id must round-trip through its
    owner only."""
    c = PSClient([s.endpoint for s in two_servers])
    c.create_sparse_table("emb", 2, initializer="zeros")
    ids = np.arange(10)
    c.push_sparse("emb", ids, np.ones((10, 2), np.float32), lr=1.0)
    # evens on server 0, odds on server 1
    assert len(two_servers[0].tables["emb"]) == 5
    assert len(two_servers[1].tables["emb"]) == 5
    vals = c.pull_sparse("emb", ids)
    np.testing.assert_allclose(vals, -1.0)
    assert c.sparse_table_size("emb") == 10
    c.close()


def test_sparse_embedding_trains_vs_dense_twin(server):
    """The PS-backed embedding must follow the same trajectory as an
    in-process dense embedding trained with plain SGD (loss parity — the
    BASELINE.md criterion for PS configs)."""
    rng = np.random.RandomState(0)
    V, D, B = 20, 8, 16
    table0 = rng.standard_normal((V, D)).astype(np.float32) * 0.1
    targets = rng.standard_normal((B, D)).astype(np.float32)
    ids_np = rng.randint(0, V, size=(B,))

    # dense twin (numpy reference)
    w = table0.copy()
    ref_losses = []
    for _ in range(5):
        e = w[ids_np]
        diff = e - targets
        ref_losses.append(float((diff ** 2).mean()))
        g = np.zeros_like(w)
        np.add.at(g, ids_np, 2.0 * diff / diff.size)
        w -= 0.5 * g

    # PS path
    c = PSClient([server.endpoint])
    emb = SparseEmbedding("emb", V, D, ps_client=c, optimizer="sgd")
    # seed table with identical init
    c.push_sparse("emb", np.arange(V),
                  -(table0 - c.pull_sparse("emb", np.arange(V))), lr=1.0)
    np.testing.assert_allclose(c.pull_sparse("emb", np.arange(V)), table0,
                               atol=1e-6)
    ids = paddle.to_tensor(ids_np.astype(np.int64))
    tgt = paddle.to_tensor(targets)
    ps_losses = []
    for _ in range(5):
        out = emb(ids)
        loss = ((out - tgt) ** 2).mean()
        loss.backward()
        ps_losses.append(float(loss.numpy()))
        emb.push_step(lr=0.5)
    np.testing.assert_allclose(ps_losses, ref_losses, rtol=1e-4)
    assert ps_losses[-1] < ps_losses[0]
    c.close()


def test_geo_trainer_syncs_every_k(server):
    c = PSClient([server.endpoint])
    lin = paddle.nn.Linear(4, 4)
    geo = GeoTrainer("geo_lin", lin.parameters(), k_steps=3, ps_client=c)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=lin.parameters())
    x = paddle.to_tensor(np.random.RandomState(1)
                         .standard_normal((8, 4)).astype(np.float32))
    synced = []
    for step in range(6):
        loss = (lin(x) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        synced.append(geo.step())
    assert synced == [False, False, True, False, False, True]
    # after a sync, server table == worker param
    np.testing.assert_allclose(c.pull_dense("geo_lin.0"),
                               lin.parameters()[0].numpy(), atol=1e-6)
    c.close()


def test_geo_two_workers_converge(server):
    """Two geo workers sharing one PS: both push deltas; both end up with
    the merged global params and a decreasing loss."""
    rng = np.random.RandomState(2)
    x = paddle.to_tensor(rng.standard_normal((16, 4)).astype(np.float32))
    y = paddle.to_tensor(rng.standard_normal((16, 2)).astype(np.float32))

    workers = []
    for _ in range(2):
        c = PSClient([server.endpoint])
        lin = paddle.nn.Linear(4, 2)
        geo = GeoTrainer("geo2", lin.parameters(), k_steps=2, ps_client=c)
        opt = paddle.optimizer.SGD(learning_rate=0.05,
                                   parameters=lin.parameters())
        workers.append((c, lin, geo, opt))

    first = last = None
    for step in range(8):
        for c, lin, geo, opt in workers:
            loss = ((lin(x) - y) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            geo.step()
            val = float(loss.numpy())
            first = val if first is None else first
            last = val
    assert last < first
    # final flush: everyone pushes outstanding deltas, then everyone pulls
    # the settled global state (the communicator's end-of-training barrier)
    for _, _, geo, _ in workers:
        geo.sync()
    for _, _, geo, _ in workers:
        geo.sync()
    w0 = workers[0][1].parameters()[0].numpy()
    w1 = workers[1][1].parameters()[0].numpy()
    np.testing.assert_allclose(w0, w1, atol=1e-5)
    for c, *_ in workers:
        c.close()


def test_save_load_roundtrip(server, tmp_path):
    c = PSClient([server.endpoint])
    c.create_sparse_table("emb", 3)
    c.create_dense_table("w", (2, 2))
    ids = np.array([1, 5, 9])
    before = c.pull_sparse("emb", ids)
    c.push_dense_grad("w", np.ones((2, 2), np.float32), lr=1.0)
    c.save(str(tmp_path))
    # clobber, then restore
    c.push_sparse("emb", ids, np.ones((3, 3), np.float32), lr=10.0)
    c.push_dense_delta("w", np.ones((2, 2), np.float32))
    c.load(str(tmp_path))
    np.testing.assert_allclose(c.pull_sparse("emb", ids), before, atol=1e-7)
    np.testing.assert_allclose(c.pull_dense("w"), -1.0)
    c.close()


def test_fleet_ps_roles_and_lifecycle():
    """fleet.init_server/run_server/init_worker/stop_worker wiring
    (reference: fleet.py:937,1038)."""
    import threading

    from paddle_tpu.distributed import fleet, ps

    ps.init(role="pserver")
    assert fleet.is_server() and not fleet.is_worker()
    server = fleet.init_server()
    t = threading.Thread(target=fleet.run_server, daemon=True)
    t.start()

    ps.init(role="trainer")
    assert fleet.is_worker()
    fleet.init_worker(endpoints=[server.endpoint])
    ps.client().create_sparse_table("e", 2)
    assert ps.client().pull_sparse("e", np.array([0])).shape == (1, 2)
    fleet.stop_worker()  # stops the server too
    t.join(timeout=10)
    assert not t.is_alive()


def test_dense_init_once_is_atomic(server):
    """N concurrent first-writers: exactly one seeds the table (GeoTrainer
    startup race)."""
    import threading

    c = PSClient([server.endpoint])
    c.create_dense_table("seed_t", (4,))
    results = []
    lock = threading.Lock()

    def worker(i):
        cc = PSClient([server.endpoint])
        won = cc.dense_init_once("seed_t", np.full(4, float(i + 1),
                                                   np.float32))
        with lock:
            results.append((i, won))
        cc.close()

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(4)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    winners = [i for i, won in results if won]
    assert len(winners) == 1
    np.testing.assert_allclose(c.pull_dense("seed_t"),
                               float(winners[0] + 1))
    c.close()


def test_rpc_many_arrays_roundtrip():
    """>10 arrays in one message must not scramble (wire order is numeric,
    not lexicographic)."""
    from paddle_tpu.distributed.ps.rpc import _encode, _decode

    import io
    import socket as socket_mod

    msg = {"arrs": [np.full((2, 2), i, np.float32) for i in range(13)]}
    raw = _encode(msg)

    class FakeSock:
        def __init__(self, buf):
            self._b = io.BytesIO(buf)

        def recv(self, n):
            return self._b.read(n)

    out = _decode(FakeSock(raw))
    for i, a in enumerate(out["arrs"]):
        np.testing.assert_array_equal(a, np.full((2, 2), i, np.float32))
