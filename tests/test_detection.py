"""Detection: DETR end-to-end + yolo_loss / generate_proposals / psroi_pool.

Reference analogue: BASELINE.md config #4 ("PP-YOLOE / DETR object detection
trains end-to-end") and the per-op tests test_yolov3_loss_op.py,
test_generate_proposals_v2_op.py, test_psroi_pool_op.py.
"""

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import ops as vops
from paddle_tpu.vision.models import (DETR, HungarianMatcher, SetCriterion,
                                      detr_resnet50)
from paddle_tpu.vision.models.detr import (box_cxcywh_to_xyxy,
                                           generalized_box_iou)


def _tiny_detr():
    return DETR(num_classes=5, num_queries=8, hidden_dim=32, nheads=4,
                num_encoder_layers=1, num_decoder_layers=1,
                backbone="resnet18", dim_feedforward=64, dropout=0.0)


def _targets():
    return [
        {"labels": np.array([1, 3]),
         "boxes": np.array([[0.3, 0.3, 0.2, 0.2],
                            [0.7, 0.6, 0.2, 0.3]], np.float32)},
        {"labels": np.array([2]),
         "boxes": np.array([[0.5, 0.5, 0.4, 0.4]], np.float32)},
    ]


class TestDETR:
    def test_forward_shapes(self):
        model = _tiny_detr()
        imgs = paddle.to_tensor(np.random.RandomState(0)
                                .rand(2, 3, 64, 64).astype(np.float32))
        out = model(imgs)
        assert list(out["pred_logits"].shape) == [2, 8, 6]  # C+1
        assert list(out["pred_boxes"].shape) == [2, 8, 4]
        b = out["pred_boxes"].numpy()
        assert (b >= 0).all() and (b <= 1).all()  # sigmoid cxcywh

    def test_trains_end_to_end(self):
        model = _tiny_detr()
        crit = SetCriterion(num_classes=5)
        imgs = paddle.to_tensor(np.random.RandomState(0)
                                .rand(2, 3, 64, 64).astype(np.float32))
        opt = paddle.optimizer.AdamW(1e-3, parameters=model.parameters())
        losses = []
        for _ in range(6):
            l = crit(model(imgs), _targets())
            l["loss"].backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(l["loss"].numpy()))
        assert losses[-1] < losses[0]

    def test_empty_targets(self):
        model = _tiny_detr()
        crit = SetCriterion(num_classes=5)
        imgs = paddle.to_tensor(np.random.RandomState(1)
                                .rand(1, 3, 64, 64).astype(np.float32))
        tgt = [{"labels": np.zeros(0, np.int64),
                "boxes": np.zeros((0, 4), np.float32)}]
        l = crit(model(imgs), tgt)
        assert np.isfinite(float(l["loss"].numpy()))
        assert float(l["loss_bbox"].numpy()) == 0.0

    def test_matcher_prefers_matching_class_and_box(self):
        """Hand-built outputs: query 1 predicts the gt box+class, query 0
        predicts garbage — the matcher must pick query 1."""
        logits = np.full((1, 2, 3), -5.0, np.float32)
        logits[0, 1, 0] = 5.0            # query 1 -> class 0
        boxes = np.array([[[0.9, 0.9, 0.05, 0.05],
                           [0.3, 0.3, 0.2, 0.2]]], np.float32)
        out = {"pred_logits": paddle.to_tensor(logits),
               "pred_boxes": paddle.to_tensor(boxes)}
        tgt = [{"labels": np.array([0]),
                "boxes": np.array([[0.3, 0.3, 0.2, 0.2]], np.float32)}]
        (qi, ti), = HungarianMatcher()(out, tgt)
        assert qi.tolist() == [1] and ti.tolist() == [0]

    def test_giou_identity_and_disjoint(self):
        import jax.numpy as jnp
        a = jnp.asarray([[0.0, 0.0, 1.0, 1.0]])
        b = jnp.asarray([[2.0, 2.0, 3.0, 3.0]])
        assert float(generalized_box_iou(a, a)[0, 0]) == pytest.approx(1.0)
        assert float(generalized_box_iou(a, b)[0, 0]) < 0  # disjoint < 0

    def test_detr_resnet50_constructs(self):
        m = detr_resnet50(num_classes=3, num_queries=4, hidden_dim=32,
                          nheads=4, num_encoder_layers=1,
                          num_decoder_layers=1, dim_feedforward=32)
        assert m.num_queries == 4


class TestYoloLoss:
    def _inputs(self, seed=0):
        rng = np.random.RandomState(seed)
        N, A, C, H, W = 2, 3, 4, 8, 8
        x = paddle.to_tensor(rng.randn(N, A * (5 + C), H, W)
                             .astype(np.float32) * 0.1)
        gt_box = paddle.to_tensor(np.array(
            [[[0.4, 0.4, 0.3, 0.3], [0, 0, 0, 0]],
             [[0.6, 0.5, 0.5, 0.4], [0.2, 0.2, 0.1, 0.1]]], np.float32))
        gt_label = paddle.to_tensor(np.array([[1, 0], [2, 3]], np.int64))
        anchors = [10, 13, 16, 30, 33, 23, 30, 61, 62, 45, 59, 119]
        return x, gt_box, gt_label, anchors

    def test_shape_and_positive(self):
        x, gb, gl, anchors = self._inputs()
        loss = vops.yolo_loss(x, gb, gl, anchors, anchor_mask=[0, 1, 2],
                              class_num=4, ignore_thresh=0.7,
                              downsample_ratio=32)
        assert list(loss.shape) == [2]
        assert (loss.numpy() > 0).all()

    def test_gradient_flows_and_training_decreases(self):
        x, gb, gl, anchors = self._inputs()
        x.stop_gradient = False
        vals = []
        for _ in range(8):
            loss = vops.yolo_loss(x, gb, gl, anchors, anchor_mask=[0, 1, 2],
                                  class_num=4, ignore_thresh=0.7,
                                  downsample_ratio=32).sum()
            loss.backward()
            with paddle.no_grad():
                x = paddle.to_tensor((x - 0.5 * x.grad).numpy())
            x.stop_gradient = False
            vals.append(float(loss.numpy()))
        assert vals[-1] < vals[0]

    def test_gt_score_weighting(self):
        """A down-weighted gt (score 0.2, mixup-style) must shrink the loss
        of the image whose gt actually matches a masked anchor (image 1;
        image 0's best anchor is #5, outside mask [0,1,2], so it carries no
        targets and is invariant by construction)."""
        x, gb, gl, anchors = self._inputs()
        full = vops.yolo_loss(x, gb, gl, anchors, anchor_mask=[0, 1, 2],
                              class_num=4, ignore_thresh=0.7,
                              downsample_ratio=32,
                              gt_score=paddle.to_tensor(
                                  np.ones((2, 2), np.float32)))
        soft = vops.yolo_loss(x, gb, gl, anchors, anchor_mask=[0, 1, 2],
                              class_num=4, ignore_thresh=0.7,
                              downsample_ratio=32,
                              gt_score=paddle.to_tensor(
                                  np.full((2, 2), 0.2, np.float32)))
        assert soft.numpy()[1] < full.numpy()[1]
        np.testing.assert_allclose(soft.numpy()[0], full.numpy()[0],
                                   rtol=1e-6)

    def test_scale_x_y_changes_ignore_mask_decode(self):
        """scale_x_y reshapes the decoded centers feeding the ignore mask;
        large logits + low thresh make threshold crossings certain."""
        rng = np.random.RandomState(0)
        x = paddle.to_tensor(rng.randn(2, 3 * 9, 8, 8)
                             .astype(np.float32) * 2.0)
        _, gb, gl, anchors = self._inputs()
        a = vops.yolo_loss(x, gb, gl, anchors, anchor_mask=[3, 4, 5],
                           class_num=4, ignore_thresh=0.1,
                           downsample_ratio=32, scale_x_y=1.0)
        b = vops.yolo_loss(x, gb, gl, anchors, anchor_mask=[3, 4, 5],
                           class_num=4, ignore_thresh=0.1,
                           downsample_ratio=32, scale_x_y=2.0)
        assert not np.allclose(a.numpy(), b.numpy())


class TestGenerateProposals:
    def test_decode_clip_nms(self):
        N, A, H, W = 1, 2, 2, 2
        scores = paddle.to_tensor(np.array(
            [[[[0.9, 0.1], [0.2, 0.3]],
              [[0.8, 0.05], [0.1, 0.6]]]], np.float32))
        deltas = paddle.to_tensor(np.zeros((N, 4 * A, H, W), np.float32))
        anchors = np.zeros((H, W, A, 4), np.float32)
        for i in range(H):
            for j in range(W):
                anchors[i, j, 0] = (j * 8, i * 8, j * 8 + 16, i * 8 + 16)
                anchors[i, j, 1] = (j * 8, i * 8, j * 8 + 32, i * 8 + 32)
        variances = np.ones_like(anchors)
        rois, probs, num = vops.generate_proposals(
            scores, deltas, paddle.to_tensor(np.array([[24.0, 24.0]],
                                                      np.float32)),
            paddle.to_tensor(anchors), paddle.to_tensor(variances),
            pre_nms_top_n=8, post_nms_top_n=4, nms_thresh=0.9,
            min_size=1.0, return_rois_num=True)
        r = rois.numpy()
        assert r.shape[1] == 4
        assert int(num.numpy()[0]) == r.shape[0] <= 4
        # zero deltas with unit variances decode back to the anchors
        # (clipped); highest-score anchor must be first
        assert (r[:, 0] >= 0).all() and (r[:, 2] <= 24).all()
        # scores sorted descending
        p = probs.numpy()
        assert (np.diff(p) <= 1e-6).all()

    def test_min_size_filters(self):
        scores = paddle.to_tensor(np.ones((1, 1, 1, 1), np.float32))
        deltas = paddle.to_tensor(np.zeros((1, 4, 1, 1), np.float32))
        anchors = paddle.to_tensor(np.array([[[[0, 0, 2, 2]]]], np.float32))
        variances = paddle.to_tensor(np.ones((1, 1, 1, 4), np.float32))
        rois, probs = vops.generate_proposals(
            scores, deltas, paddle.to_tensor(np.array([[100.0, 100.0]],
                                                      np.float32)),
            anchors, variances, min_size=50.0)
        assert rois.numpy().shape[0] == 0


class TestPsroiPool:
    def test_position_sensitive_channel_pick(self):
        ph = pw = 2
        out_c = 3
        C = out_c * ph * pw
        # each input channel filled with its own index
        x = np.zeros((1, C, 8, 8), np.float32)
        for c in range(C):
            x[0, c] = c
        boxes = paddle.to_tensor(np.array([[0.0, 0.0, 8.0, 8.0]],
                                          np.float32))
        out = vops.psroi_pool(paddle.to_tensor(x), boxes,
                              paddle.to_tensor(np.array([1], np.int32)),
                              output_size=2)
        got = out.numpy()
        assert got.shape == (1, out_c, ph, pw)
        # bin (i,j) of output channel oc must read channel oc*4 + i*2 + j
        for oc in range(out_c):
            for i in range(ph):
                for j in range(pw):
                    np.testing.assert_allclose(got[0, oc, i, j],
                                               oc * 4 + i * 2 + j,
                                               atol=1e-4)

    def test_channel_mismatch_rejected(self):
        with pytest.raises(ValueError):
            vops.psroi_pool(
                paddle.to_tensor(np.zeros((1, 7, 8, 8), np.float32)),
                paddle.to_tensor(np.zeros((1, 4), np.float32)),
                paddle.to_tensor(np.array([1], np.int32)), 2)
