"""KV-cached autoregressive generation.

Reference: the decode path (masked_multihead_attention_kernel.cu, paddlenlp
generate): incremental decoding with a cache must produce exactly the same
tokens as full-recompute greedy decoding."""

import numpy as np
import pytest

import paddle_tpu as paddle


class TestGenerate:
    def _model(self, **kw):
        from paddle_tpu.models import GPTConfig, GPTForCausalLM
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=4, max_seq_len=32,
                        use_flash_attention=False, **kw)
        paddle.seed(31)
        m = GPTForCausalLM(cfg)
        m.eval()
        return m

    @pytest.mark.parametrize("use_rope", [False, True])
    def test_greedy_matches_full_recompute(self, use_rope):
        """Cached decode == argmax over a fresh full forward at every step
        (the no-cache reference decoder)."""
        model = self._model(use_rope=use_rope)
        ids = paddle.randint(0, 64, [2, 5])
        out = model.generate(ids, max_new_tokens=6)
        got = np.asarray(out.numpy())
        assert got.shape == (2, 11)
        assert np.array_equal(got[:, :5], np.asarray(ids.numpy()))

        # reference: re-run the full (uncached) forward each step
        cur = np.asarray(ids.numpy())
        for _ in range(6):
            logits = model(paddle.to_tensor(cur)).numpy()
            nxt = np.argmax(np.asarray(logits)[:, -1], axis=-1)
            cur = np.concatenate([cur, nxt[:, None].astype(cur.dtype)], 1)
        assert np.array_equal(got, cur), (got, cur)

    def test_eos_freezes_row(self):
        model = self._model()
        ids = paddle.randint(0, 64, [2, 4])
        # pick eos = the first greedily generated token of row 0 so it hits
        first = np.asarray(model.generate(ids, max_new_tokens=1)
                           .numpy())[0, -1]
        out = np.asarray(model.generate(ids, max_new_tokens=5,
                                        eos_token_id=int(first)).numpy())
        row = out[0, 4:]
        hit = np.where(row == first)[0]
        assert hit.size > 0
        assert np.all(row[hit[0]:] == first), row  # frozen after eos

    def test_sampling_reproducible_and_in_range(self):
        model = self._model()
        ids = paddle.randint(0, 64, [2, 4])
        a = np.asarray(model.generate(ids, max_new_tokens=5, do_sample=True,
                                      temperature=0.8, top_k=8,
                                      seed=7).numpy())
        b = np.asarray(model.generate(ids, max_new_tokens=5, do_sample=True,
                                      temperature=0.8, top_k=8,
                                      seed=7).numpy())
        assert np.array_equal(a, b)
        assert a.min() >= 0 and a.max() < 64

    def test_moe_model_generates(self):
        model = self._model(num_experts=2)
        out = model.generate(paddle.randint(0, 64, [2, 4]),
                             max_new_tokens=3)
        assert np.asarray(out.numpy()).shape == (2, 7)


class TestBlockMHA:
    def test_paged_matches_contiguous(self):
        """Paged (block-table) decode attention == the contiguous-cache
        MMHA on the same logical K/V — pages only change the storage
        layout (reference: block_multi_head_attention_kernel.cu)."""
        import jax.numpy as jnp
        from paddle_tpu.incubate.nn.functional import (
            block_multihead_attention, masked_multihead_attention)

        rng = np.random.default_rng(4)
        B, nh, hd, page = 2, 2, 8, 4
        n_pages, max_pages = 8, 3
        H = nh * hd
        pos = np.asarray([5, 2], np.int32)
        # logical histories
        hist_k = rng.normal(size=(B, nh, max_pages * page, hd)) \
            .astype(np.float32)
        hist_v = rng.normal(size=(B, nh, max_pages * page, hd)) \
            .astype(np.float32)
        for b in range(B):
            hist_k[b, :, pos[b]:] = 0
            hist_v[b, :, pos[b]:] = 0
        # scatter histories into a shuffled page pool
        tables = np.asarray([[3, 1, 6], [0, 4, 2]], np.int32)
        kc = np.zeros((n_pages, nh, page, hd), np.float32)
        vc = np.zeros((n_pages, nh, page, hd), np.float32)
        for b in range(B):
            for pi in range(max_pages):
                kc[tables[b, pi]] = hist_k[b, :, pi * page:(pi + 1) * page]
                vc[tables[b, pi]] = hist_v[b, :, pi * page:(pi + 1) * page]
        x = rng.normal(size=(B, 3 * H)).astype(np.float32)

        out, kc2, vc2 = block_multihead_attention(
            paddle.to_tensor(x), paddle.to_tensor(kc), paddle.to_tensor(vc),
            paddle.to_tensor(pos), paddle.to_tensor(tables))

        # contiguous-cache reference via MMHA
        cache = np.stack([hist_k, hist_v])  # [2, B, nh, S, hd]
        ref_out, _ = masked_multihead_attention(
            paddle.to_tensor(x), paddle.to_tensor(cache),
            sequence_lengths=paddle.to_tensor(pos))
        assert np.allclose(np.asarray(out.numpy()),
                           np.asarray(ref_out.numpy()), atol=1e-4)
        # the write landed in the right page slot
        qkv = x.reshape(B, 3, nh, hd)
        for b in range(B):
            pg, sl = tables[b, pos[b] // page], pos[b] % page
            assert np.allclose(np.asarray(kc2.numpy())[pg, :, sl],
                               qkv[b, 1], atol=1e-6)


class TestMaskedMHA:
    def test_matches_dense_attention(self):
        """incubate MMHA (single decode step vs cache) == dense softmax
        attention over the valid prefix."""
        import jax.numpy as jnp
        from paddle_tpu.incubate.nn.functional import \
            masked_multihead_attention

        rng = np.random.default_rng(2)
        B, nh, S, hd = 2, 4, 8, 16
        H = nh * hd
        pos = np.asarray([3, 5], np.int32)   # current lengths per row
        cache = np.zeros((2, B, nh, S, hd), np.float32)
        for b in range(B):
            cache[:, b, :, :pos[b]] = rng.normal(
                size=(2, nh, pos[b], hd)).astype(np.float32)
        x = rng.normal(size=(B, 3 * H)).astype(np.float32)

        out, new_cache = masked_multihead_attention(
            paddle.to_tensor(x), paddle.to_tensor(cache),
            sequence_lengths=paddle.to_tensor(pos))
        out = np.asarray(out.numpy())
        new_cache = np.asarray(new_cache.numpy())

        qkv = x.reshape(B, 3, nh, hd)
        for b in range(B):
            t = pos[b]
            ck = cache[0, b].copy()
            cv = cache[1, b].copy()
            ck[:, t] = qkv[b, 1]
            cv[:, t] = qkv[b, 2]
            assert np.allclose(new_cache[0, b], ck, atol=1e-6)
            lg = np.einsum("hd,hsd->hs", qkv[b, 0] / np.sqrt(hd),
                           ck[:, :t + 1])
            p = np.exp(lg - lg.max(-1, keepdims=True))
            p /= p.sum(-1, keepdims=True)
            o = np.einsum("hs,hsd->hd", p, cv[:, :t + 1])
            assert np.allclose(out[b].reshape(nh, hd), o, atol=1e-4), b
