"""Distributed tests on the 8-device virtual CPU mesh (reference pattern:
test/collective/fleet/hybrid_parallel_mp_layers.py — parity between parallel
and single-process runs)."""

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn


def np_t(x):
    return np.asarray(x.numpy())


@pytest.fixture(scope="module")
def mesh8():
    import jax
    if jax.device_count() < 8:
        pytest.skip("needs 8 devices")
    from paddle_tpu.distributed import fleet
    strategy = fleet.DistributedStrategy()
    strategy.hybrid_configs = {"dp_degree": 2, "mp_degree": 2, "pp_degree": 2}
    hcg = fleet.init(is_collective=True, strategy=strategy)
    yield hcg
    fleet._reset()  # don't leak pp=2 topology into other modules


class TestTopology:
    def test_hcg(self, mesh8):
        from paddle_tpu.distributed import fleet
        hcg = fleet.get_hybrid_communicate_group()
        assert hcg.get_model_parallel_world_size() == 2
        assert hcg.get_pipe_parallel_world_size() == 2
        assert hcg.get_data_parallel_world_size() == 2
        topo = hcg.topology()
        assert topo.world_size() == 8
        groups = topo.get_comm_list("mp")
        assert len(groups) == 4 and len(groups[0]) == 2

    def test_mesh_axes(self, mesh8):
        mesh = paddle.distributed.get_mesh()
        assert dict(mesh.shape) == {"pp": 2, "dp": 2, "sharding": 1,
                                    "sep": 1, "mp": 2}


class TestTPParity(object):
    def test_tp_model_matches_serial(self, mesh8):
        """TP=2 compiled result == plain serial execution (same weights)."""
        from paddle_tpu.distributed import fleet, DistributedEvalStep
        paddle.seed(0)
        col = fleet.ColumnParallelLinear(8, 16, has_bias=True,
                                         gather_output=False)
        row = fleet.RowParallelLinear(16, 8, input_is_parallel=True)
        model = nn.Sequential(col, row)
        x = paddle.randn([4, 2, 8])
        eager = np_t(model(x))  # single-device serial math
        step = DistributedEvalStep(model)
        dist = np_t(step(x))
        assert np.allclose(eager, dist, atol=1e-4)

    def test_vocab_parallel_embedding(self, mesh8):
        from paddle_tpu.distributed import fleet, DistributedEvalStep
        emb = fleet.VocabParallelEmbedding(32, 16)
        ids = paddle.randint(0, 32, [2, 6])
        eager = np_t(emb(ids))
        dist = np_t(DistributedEvalStep(emb)(ids))
        assert np.allclose(eager, dist, atol=1e-5)


class TestShardTensor:
    def test_shard_and_reshard(self, mesh8):
        import jax
        from paddle_tpu.distributed import ProcessMesh, Shard, Replicate
        mesh = ProcessMesh(np.arange(8).reshape(4, 2), ["x", "y"])
        t = paddle.distributed.shard_tensor(
            paddle.randn([8, 4]), mesh, [Shard(0), Replicate()])
        assert t.is_dist
        shard_shape = next(iter(
            t._data.addressable_shards)).data.shape
        assert shard_shape == (2, 4)
        r = paddle.distributed.reshard(t, mesh, [Replicate(), Shard(1)])
        shard_shape = next(iter(r._data.addressable_shards)).data.shape
        assert shard_shape == (8, 2)

    def test_placements_to_spec(self):
        from paddle_tpu.distributed.auto_parallel import (
            ProcessMesh, Replicate, Shard, _spec_with_names)
        mesh = ProcessMesh(np.arange(4).reshape(2, 2), ["a", "b"])
        spec = _spec_with_names([Shard(1), Replicate()], mesh, 3)
        assert spec == __import__("jax").sharding.PartitionSpec(None, "a", None)


class TestFSDP:
    def test_annotations(self, mesh8):
        from paddle_tpu.distributed.fleet.parallel_apply import (
            apply_fsdp_annotations)
        from paddle_tpu.distributed.env import _HYBRID_DEGREES
        # force a sharding degree for the annotation logic
        import paddle_tpu.distributed.env as env
        old = dict(env._HYBRID_DEGREES)
        env._HYBRID_DEGREES["sharding"] = 2
        try:
            net = nn.Linear(64, 64)
            apply_fsdp_annotations(net)
            assert net.weight.placements is not None
            assert "sharding" in str(net.weight.placements)
        finally:
            env._HYBRID_DEGREES.update(old)


class TestCollectivesDegenerate:
    def test_single_process_collectives(self):
        t = paddle.to_tensor([1.0, 2.0])
        paddle.distributed.all_reduce(t)
        assert np.allclose(np_t(t), [1, 2])
        outs = []
        paddle.distributed.all_gather(outs, t)
        assert len(outs) == 1
        paddle.distributed.broadcast(t, 0)
        paddle.distributed.barrier()
        assert paddle.distributed.get_world_size() == 1

    def test_data_parallel_wrapper(self):
        net = nn.Linear(2, 2)
        dp = paddle.DataParallel(net)
        out = dp(paddle.randn([3, 2]))
        assert out.shape == [3, 2]
        out.sum().backward()
        dp.apply_collective_grads()
        assert net.weight.grad is not None


class TestPipeline:
    def test_pipeline_matches_sequential(self, mesh8):
        """Compiled ppermute pipeline == sequential stage execution."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.distributed.pipeline import pipeline_apply
        from paddle_tpu.core.state import STATE

        w = jnp.stack([jnp.eye(4) * (i + 1) for i in range(2)])  # [pp=2,4,4]

        def stage_fn(sp, h):
            return jnp.tanh(h @ sp)

        x = jnp.ones((4, 4))
        # sequential reference
        ref = x
        for s in range(2):
            ref = stage_fn(w[s], ref)

        def run(wv, xv):
            STATE.tracing_depth += 1
            try:
                return pipeline_apply(stage_fn, {"w": wv}, xv, 2)
            finally:
                STATE.tracing_depth -= 1

        def run2(wv, xv):
            return pipeline_apply(lambda sp, h: stage_fn(sp["w"], h),
                                  {"w": wv}, xv, 2)

        mesh = paddle.distributed.get_mesh()
        STATE.tracing_depth += 1
        try:
            out = jax.jit(lambda wv, xv: pipeline_apply(
                lambda sp, h: stage_fn(sp["w"], h), {"w": wv}, xv, 2))(w, x)
        finally:
            STATE.tracing_depth -= 1
        assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-5)

    def test_pipeline_with_aux_matches_sequential(self, mesh8):
        """with_aux=True carries a per-stage scalar through the compiled
        ppermute schedule, AVERAGED over microbatches (so mean-style aux
        losses match pp=1 instead of scaling with M).  For an additive
        (sum-over-rows) aux with an even row split, the microbatch mean is
        exactly whole_batch_aux / M.  Regression for the MoE aux loss being
        silently dropped on pipeline meshes."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.distributed.pipeline import pipeline_apply
        from paddle_tpu.core.state import STATE

        w = jnp.stack([jnp.eye(4) * (i + 1) for i in range(2)])

        def stage_fn(sp, h):
            return jnp.tanh(h @ sp["w"]), jnp.sum(h.astype(jnp.float32) ** 2)

        x = jnp.arange(16, dtype=jnp.float32).reshape(4, 4) / 16.0
        ref, aux_ref = x, 0.0
        for s in range(2):
            ref, a = stage_fn({"w": w[s]}, ref)
            aux_ref += float(a)

        STATE.tracing_depth += 1
        try:
            out, aux = jax.jit(lambda wv, xv: pipeline_apply(
                stage_fn, {"w": wv}, xv, 2, with_aux=True))(w, x)
        finally:
            STATE.tracing_depth -= 1
        assert np.allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
        assert np.allclose(float(aux), aux_ref / 2, rtol=1e-5), \
            (float(aux), aux_ref)  # M=2 microbatches -> mean = sum/2

        # gradients flow through the aux carry
        STATE.tracing_depth += 1
        try:
            g = jax.jit(jax.grad(lambda wv: pipeline_apply(
                stage_fn, {"w": wv}, x, 2, with_aux=True)[1]))(w)
        finally:
            STATE.tracing_depth -= 1
        assert float(jnp.abs(g).max()) > 1e-8

    def test_pipeline_layer_segmentation(self):
        from paddle_tpu.distributed.fleet import LayerDesc, PipelineLayer
        descs = [LayerDesc(nn.Linear, 4, 4) for _ in range(4)]
        pl = PipelineLayer(descs, num_stages=2, loss_fn=None)
        assert pl.segment_bounds == [0, 2, 4]
        assert len(pl.get_stage_layers(0)) == 2
        out = pl(paddle.randn([2, 4]))
        assert out.shape == [2, 4]


class TestSequenceParallelLinears:
    def test_sp_pair_matches_dense(self, mesh8):
        """ColumnSequenceParallelLinear -> RowSequenceParallelLinear ==
        dense matmul chain (reference: sequence_parallel_utils.py:427,562 —
        the SP pair is numerically the TP pair, only the collective moves
        from all-reduce to all-gather/reduce-scatter)."""
        import jax
        import jax.numpy as jnp
        from paddle_tpu.distributed.fleet import (
            ColumnSequenceParallelLinear, RowSequenceParallelLinear,
            ScatterOp, GatherOp)

        paddle.seed(3)
        col = ColumnSequenceParallelLinear(8, 16, has_bias=True)
        row = RowSequenceParallelLinear(16, 8, has_bias=True)
        x = paddle.randn([4, 8, 8])  # [b, s, h]

        y = GatherOp.apply(row(paddle.nn.functional.gelu(
            col(ScatterOp.apply(x)))))

        w1, b1 = np.asarray(col.weight.numpy()), np.asarray(
            col.bias.numpy())
        w2, b2 = np.asarray(row.weight.numpy()), np.asarray(
            row.bias.numpy())
        xn = np.asarray(x.numpy())
        hidden = xn @ w1 + b1
        gelu = 0.5 * hidden * (1 + np.vectorize(__import__("math").erf)(
            hidden / np.sqrt(2)))
        ref = gelu @ w2 + b2
        assert np.allclose(np.asarray(y.numpy()), ref, atol=1e-4), \
            np.abs(np.asarray(y.numpy()) - ref).max()


class TestGPTHybrid:
    def test_gpt_dist_train(self, mesh8):
        from paddle_tpu.distributed import DistributedTrainStep
        from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                       GPTPretrainingCriterion)
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=2, max_seq_len=16,
                        use_flash_attention=False)
        model = GPTForCausalLM(cfg)
        crit = GPTPretrainingCriterion()
        opt = paddle.optimizer.AdamW(3e-3, parameters=model.parameters())
        ids = paddle.randint(0, 64, [4, 16])
        lab = paddle.randint(0, 64, [4, 16])
        step = DistributedTrainStep(model, lambda m, x, l: crit(m(x), l), opt)
        l0 = float(step(ids, lab).numpy())
        for _ in range(3):
            l = float(step(ids, lab).numpy())
        assert np.isfinite(l) and l < l0

    def test_gpt_moe_pp_aux_carried(self, mesh8):
        """MoE GPT on a pp=2 mesh: the aux loss rides the pipeline carry
        (was silently 0 before pipeline_apply(with_aux=True)) and the model
        trains with aux in the objective."""
        from paddle_tpu.distributed import DistributedTrainStep
        from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                       GPTPretrainingCriterion)
        cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                        num_heads=2, max_seq_len=16, num_experts=2,
                        use_flash_attention=False)
        paddle.seed(9)
        model = GPTForCausalLM(cfg)
        crit = GPTPretrainingCriterion()
        opt = paddle.optimizer.AdamW(3e-3, parameters=model.parameters())
        ids = paddle.randint(0, 64, [4, 16])
        lab = paddle.randint(0, 64, [4, 16])

        def loss_fn(m, x, l):
            return crit(m(x), l) + 0.01 * m.moe_aux_loss()

        step = DistributedTrainStep(model, loss_fn, opt)
        l0 = float(step(ids, lab).numpy())
        for _ in range(3):
            l = float(step(ids, lab).numpy())
        assert np.isfinite(l) and l < l0
        # eager forward (sequential path) reports a positive aux
        model.eval()
        model(ids)
        assert float(model.moe_aux_loss().numpy()) > 0


class TestCheckpoint:
    def test_save_load_state_dict(self, tmp_path):
        net = nn.Linear(4, 4)
        sd = net.state_dict()
        paddle.distributed.save_state_dict(sd, str(tmp_path))
        w_orig = np_t(net.weight).copy()
        net.weight.set_value(paddle.zeros([4, 4]))
        paddle.distributed.load_state_dict(net.state_dict(), str(tmp_path))
        assert np.allclose(np_t(net.weight), w_orig)
