"""Benchmark: GPT causal-LM training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} for the
flagship leg, with per-leg detail under "legs".

Baseline anchor (BASELINE.md): the reference publishes no in-repo numbers;
the driver-defined north star is >=45% GPT MFU.  vs_baseline is true
model-FLOPs utilisation from 6*N FLOPs/token against the v5e **bf16** peak
of 197 TFLOP/s (394 TFLOP/s is the int8 number).

Legs (perf round 5):
- gpt760m (flagship MFU leg): "GPT-3 Large", batch 8 x 1024,
  recompute='selective_lean' (saves qkv+attn_out only; fc1 replays in bwd)
  — the largest model whose AdamW state (bf16 params + fp32 master + 2
  fp32 moments ~ 10.6G) fits the 15.75G chip.  Measured 0.468 MFU (512/512 flash blocks, r5 sweep).
- gpt125m (regression leg): round-4's config, batch 16 x 1024, selective
  remat — small-model overhead regression guard.  Runs twice: single-step
  dispatch, then fused multi-step dispatch (``fused_steps=K``, one XLA
  launch per K steps) — the reported ``fused_speedup`` is the
  dispatch-amortisation win on the leg most exposed to per-step python
  overhead.
- gpt125m_serve (serving leg): 64 staggered mixed-length requests through
  ``serving.LLMEngine`` (continuous batching over the KV slot arena),
  with the first few verified token-identical against sequential
  ``GPT.generate`` — reports decode tokens/s for both, ``serve_speedup``,
  and TTFT / inter-token / queue-wait latency percentiles
  (p50/p95/p99 in ms) from the engine's mergeable histograms.
- gpt125m_paged (paged-KV leg): the serving workload through
  ``LLMEngine(kv_layout="paged")`` — a mixed-length request set against
  the legacy slot arena at the SAME KV HBM budget (the block pool is
  sized to the slot arena's token capacity), gating ≥2× peak admitted
  concurrent requests; plus a 64-request shared-system-prompt workload
  reporting TTFT p50/p95 and gating prefix-cache hits with strictly
  fewer prefill-chunk launches than a no-cache twin; decode tok/s
  parity vs the slot engine is reported informationally.
- gpt125m_tiered (KV-tiering leg): two-pass session traffic (every
  prompt queried twice) through paged engines whose block pools are cut
  to 1/2 and 1/4 of the working set with a pinned host-RAM KV tier
  covering the difference — cold radix leaves spill to host instead of
  being freed and page back on the second visit.  Gates token identity
  to sequential ``generate``, zero sheds under oversubscription, live
  spill/restore traffic, and decode tok/s at 2x oversubscription >=0.5x
  the ample-pool base; a 2-replica tiered fleet replay gates the
  router's host-aware prefix-affinity wins (``prefix_routed``) and the
  zero-lost invariant.
- gpt125m_spec (speculative-decoding leg): an aligned draft/target pair
  (shared embeddings, zeroed transformer blocks — acceptance ~1.0, so the
  leg measures the draft/verify machinery's ceiling) served greedily by
  ``LLMEngine(draft_model=..., kv_layout="paged")`` vs the non-spec paged
  baseline on the same prompts — reports acceptance rate, draft/verify
  dispatch counts, and net decode tok/s, gating token identity, zero
  steady retraces, ``accepted + rejected == drafted`` and ≥1.3× speedup.
- gpt125m_fleet (elastic-fleet leg): the same seeded request set through
  a 2-replica ``serving.ServingFleet`` clean, then with one replica
  killed mid-decode (``faultinject`` ``replica_crash``) — reports decode
  tokens/s for both and ``churn_retention``, and gates the durability
  invariants (zero lost requests, churn output token-identical to clean).
- gpt125m_mesh / gpt760m_mesh (multi-chip SPMD legs): the same fused
  training loop run mesh-native (``CompiledTrainStep(mesh=...)``, sharded
  donated carry, data-parallel batch staging) on the ``PTPU_MESH`` mesh
  (default ``dp2``; e.g. ``dp4`` or ``dp2mp2``), against a mesh(1) run of
  the identical code path as the per-chip baseline.  Reports total tok/s,
  tok/s/chip, weak-scaling efficiency ``(tok/s / n_chips) / tok/s(1)``
  and per-chip MFU; gates zero steady-state retraces/hydrates/binds and
  dispatches == steps/K on the mesh path, and ≥70% dp scaling efficiency
  on real chips (forced-host CPU "devices" share cores, so the scaling
  number is informational there).
- gpt760m_servemp (tensor-parallel serving leg, PTPU_BENCH=servemp with
  PTPU_MESH=mp2): the paged engine run mesh-native over the StateArena
  (``LLMEngine(mesh=...)`` — KV pool head-sharded, Megatron-sharded
  weights, replicated block-table/sampling operands, in-graph collectives
  only) against the unsharded engine at EQUAL admitted capacity.
  Reports decode tok/s/chip and per-chip KV-pool / weight HBM bytes;
  gates token identity, zero steady retraces, per-chip KV+weight bytes
  <= 0.6x the single-chip figure, and decode tok/s >= 0.9x unsharded
  (the 760m flagship on TPU; a 125m CPU-fallback twin off-TPU).
- gpt125m_multitenant (multi-tenant LoRA serving leg): 6 adapter tenants
  through a 2-replica fleet whose per-replica AdapterArena holds only 4,
  so cold tenants page in and the LRU evicts idle ones.  A FAIR
  round-robin pass (tenants + base rows in one heterogeneous batch) and
  a NOISY pass (tenant 0 floods, plus an injected ``adapter_load_drop``)
  report decode tok/s, per-tenant-bucket TTFT/ITL tails, the flood
  bucket's ITL-p95 skew, and arena traffic (loads / evictions /
  arena_bytes / routed affinity wins); gates zero lost, token identity
  across repeats, recovery from the dropped load, and zero steady
  retraces — ONE compiled decode program serves every tenant mix.
Every training leg embeds a compact "metrics" block (loss / grad-norm /
tok/s / step-time / MFU stats from the zero-sync in-graph MetricsLogger
accumulators) plus a "goodput" block (the profiler.goodput wall-clock
ledger: compile/step bucket split and the accounted fraction); the serve
and fleet legs embed TTFT / inter-token / queue-wait percentiles, run
their measured pass under request tracing (sample=1 — the parity gates
prove it adds zero syncs/retraces) and embed a "trace" stage breakdown
saying WHERE the tail lives (queue vs prefill vs decode p50/p99/share);
the fleet leg additionally smoke-hits the live ops endpoint (OpsServer
/healthz + /traces over HTTP, ephemeral port) while the fleet is up; the
ckpt leg embeds save-latency percentiles; the mesh legs embed
per-compiled-program HBM bytes ("hbm") captured via XLA memory analysis
under FLAGS_device_telemetry.  The serve / paged / spec legs embed a
"devicetime" block (per-program device-time share / mean / MFU from the
FLAGS_device_time_sample ledger, captured in a short UNTIMED post-window
pass so the sampling fences never touch a gated number) —
``bench_compare.py --attribute`` diffs these shares to name the program
behind any regression.
Set PTPU_BENCH=125m|760m|serve|paged|paged_q|tiered|spec|ckpt|fleet|disagg|mesh|mesh760m|servemp|multitenant
to run a single leg.  PTPU_FUSED_STEPS sets the fused window length K (default 4; 1
disables the fused leg).  PTPU_MESH picks the mesh leg's axis degrees.
"""

import itertools
import json
import os
import time

import numpy as np


def _metrics_summary(logger, keys=("loss", "grad_norm", "tok_s",
                                  "step_time_s", "mfu")):
    """Compact per-metric stats from a ``MetricsLogger`` for the leg JSON."""
    if logger is None:
        return {}
    return {k: {f: round(float(x), 6) for f, x in s.items()}
            for k, s in logger.summary().items() if k in keys}


def _goodput_summary(ledger):
    """Compact wall-clock ledger block for the leg JSON (see
    profiler.goodput): where every second went, and how much of it was
    attributed to a named bucket (>=99% or the phase timings lie)."""
    r = ledger.report()
    return {"goodput": round(r["goodput"], 4),
            "accounted": round(r["accounted"], 4),
            "wall_s": round(r["wall_s"], 4),
            "buckets_s": {k: round(v, 4)
                          for k, v in r["buckets_s"].items() if v}}


def _sampled_devicetime(run_fn, sample=4, top=8):
    """Per-program device-time/MFU attribution block for one leg.

    Runs ``run_fn`` (a short UNTIMED window on the leg's already-warm
    engine) with ``FLAGS_device_time_sample=N`` + device telemetry on, so
    the ledger joins sampled fence times with AOT FLOPs/HBM stats, then
    restores the flags and returns ``devicetime.bench_block``.  Always
    runs AFTER the leg's gated timing windows: the sampled syncs (and the
    one-off AOT captures) never perturb a gated number."""
    from paddle_tpu.core import flags as _flags
    from paddle_tpu.profiler import devicetime
    saved = {k: _flags.flag(k) for k in ("FLAGS_device_time_sample",
                                         "FLAGS_device_telemetry")}
    devicetime.reset()
    _flags.set_flags({"FLAGS_device_time_sample": int(sample),
                      "FLAGS_device_telemetry": True})
    try:
        run_fn()
        block = devicetime.bench_block(top=top)   # flags still live: the
        # block records the sample rate + joined MFU it measured with
    finally:
        _flags.set_flags(saved)
    devicetime.reset()
    return block


def _run_leg(cfg, batch, seq, iters, rounds, fused_steps=1):
    import paddle_tpu as paddle
    from paddle_tpu.io import Window
    from paddle_tpu.jit import CompiledTrainStep
    from paddle_tpu.models import GPTForCausalLM, GPTPretrainingCriterion
    from paddle_tpu.profiler.goodput import GoodputLedger

    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion()
    opt = paddle.optimizer.AdamW(1e-4, parameters=model.parameters())
    ids = paddle.randint(0, cfg.vocab_size, [batch, seq])
    labels = paddle.randint(0, cfg.vocab_size, [batch, seq])

    def loss_fn(m, x, l):
        return crit(m(x), l)

    k = max(1, int(fused_steps))
    # metrics=True: in-graph telemetry rides the donated carry — the MFU
    # this leg reports is also derivable from the harvested series
    step = CompiledTrainStep(model, loss_fn, opt, fused_steps=k,
                             metrics=True)
    if k > 1:
        win = Window(
            (paddle.to_tensor(np.stack([np.asarray(ids.numpy())] * k)),
             paddle.to_tensor(np.stack([np.asarray(labels.numpy())] * k))),
            k)
        dispatch = lambda: step(win)
    else:
        dispatch = lambda: step(ids, labels)
    # warmup / compile, timed per phase: 2 warmup dispatches in both modes.
    # Single-step mode traces 2 structures (empty accs then full); fused
    # mode runs window 1 as the priming single-step fallback (both acc
    # structures) and window 2 as the scan compile.  compile_s covers
    # hydrate + all traces + XLA compiles; first_step_s is the first fully
    # cached dispatch; steady_step_s is the measured median.
    ledger = GoodputLedger()
    ledger.start()
    with ledger.bucket("compile"):
        t0 = time.perf_counter()
        dispatch()
        dispatch().numpy()
        compile_s = time.perf_counter() - t0
    with ledger.bucket("step"):
        t0 = time.perf_counter()
        dispatch().numpy()
        first_step_s = time.perf_counter() - t0

    n_windows = max(1, iters // k)
    rates = []
    for _ in range(rounds):
        with ledger.bucket("step"):
            t0 = time.perf_counter()
            for _ in range(n_windows):
                loss = dispatch()
            loss.numpy()  # sync
            dt = time.perf_counter() - t0
        rates.append(batch * seq * k * n_windows / dt)
    ledger.stop()
    tokens_per_sec = float(np.median(rates))
    spread = (float(np.max(rates) - np.min(rates)) / tokens_per_sec
              if len(rates) > 1 else 0.0)
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    phases = {"compile_s": round(compile_s, 4),
              "first_step_s": round(first_step_s, 4),
              "steady_step_s": round(batch * seq / tokens_per_sec, 6)}
    step.metrics_flush()  # harvest pending device refs at the leg boundary
    msum = _metrics_summary(step.metrics)
    gput = _goodput_summary(ledger)
    del step, model, opt  # free HBM before the next leg
    return tokens_per_sec, spread, n_params, phases, msum, gput


def _run_ckpt_leg(cfg, batch, seq, iters, fused_steps=1,
                  save_every_windows=2, seed=0):
    """Checkpointed-training overhead: the same steady dispatch loop run
    twice — bare, then with async ``resilience.CheckpointManager`` saves
    every ``save_every_windows`` windows (disk writes overlap the next
    window).  Reports the throughput overhead fraction and asserts the
    one-counter-gated-sync-per-save budget."""
    import tempfile

    import paddle_tpu as paddle
    from paddle_tpu.io import Window
    from paddle_tpu.jit import CompiledTrainStep
    from paddle_tpu.models import GPTForCausalLM, GPTPretrainingCriterion
    from paddle_tpu.profiler import counters
    from paddle_tpu.resilience import CheckpointManager

    paddle.seed(seed)
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion()
    opt = paddle.optimizer.AdamW(1e-4, parameters=model.parameters())
    ids = paddle.randint(0, cfg.vocab_size, [batch, seq])
    labels = paddle.randint(0, cfg.vocab_size, [batch, seq])

    def loss_fn(m, x, l):
        return crit(m(x), l)

    k = max(1, int(fused_steps))
    step = CompiledTrainStep(model, loss_fn, opt, fused_steps=k)
    if k > 1:
        win = Window(
            (paddle.to_tensor(np.stack([np.asarray(ids.numpy())] * k)),
             paddle.to_tensor(np.stack([np.asarray(labels.numpy())] * k))),
            k)
        dispatch = lambda: step(win)
    else:
        dispatch = lambda: step(ids, labels)
    dispatch()
    dispatch().numpy()  # warm: all traces + compiles done

    n_windows = max(save_every_windows, iters // k)
    t0 = time.perf_counter()
    for _ in range(n_windows):
        loss = dispatch()
    loss.numpy()
    base_s = time.perf_counter() - t0

    with tempfile.TemporaryDirectory() as ckdir:
        mgr = CheckpointManager(ckdir, keep_last=2, async_save=True)
        before = counters.snapshot()
        t0 = time.perf_counter()
        gs = 0
        for i in range(n_windows):
            loss = dispatch()
            gs += k
            if (i + 1) % save_every_windows == 0:
                mgr.save(step, gs, blocking=False)
        loss.numpy()
        mgr.wait()
        ckpt_s = time.perf_counter() - t0
        delta = counters.delta(before)

    from paddle_tpu.profiler import metrics as _pm
    saves = delta.get("resilience.saves", 0)
    tokens = batch * seq * k * n_windows
    save_h = _pm.get_histogram("resilience.save_ms").summary()
    leg = {"fused_steps": k,
           "save_ms_p50": round(save_h["p50"], 2),
           "save_ms_p99": round(save_h["p99"], 2),
           "windows": n_windows,
           "async_saves": saves,
           "tokens_per_sec": round(tokens / max(ckpt_s, 1e-9), 2),
           "bare_tokens_per_sec": round(tokens / max(base_s, 1e-9), 2),
           "ckpt_overhead_frac": round(max(0.0, ckpt_s / max(base_s, 1e-9)
                                           - 1.0), 4),
           "save_ms_total": delta.get("resilience.save_ms", 0),
           "syncs": delta.get("jit.syncs", 0),
           "retraces": delta.get("jit.traces", 0),
           "rehydrates": delta.get("jit.hydrates", 0)}
    if leg["syncs"] != saves or leg["retraces"] or leg["rehydrates"]:
        raise AssertionError(
            f"checkpoint leg broke the one-sync-per-save budget: {leg}")
    del step, model, opt
    return leg


def _latency_ms(hist):
    """Compact p50/p95/p99 (+count/mean) in ms from an ns histogram."""
    s = hist.summary()
    return {"count": s["count"],
            "mean_ms": round(s["mean"] / 1e6, 3),
            "p50_ms": round(s["p50"] / 1e6, 3),
            "p95_ms": round(s["p95"] / 1e6, 3),
            "p99_ms": round(s["p99"] / 1e6, 3)}


def _run_serve_leg(cfg, n_requests=64, max_new=64, max_slots=8,
                   min_bucket=8, n_verify=8, seed=0):
    """Continuous-batching serving vs sequential generate.  The engine
    serves ``n_requests`` staggered mixed-length requests (its TTFT /
    inter-token-latency / queue-wait histograms give the leg's p50/p95/p99
    tail); the first ``n_verify`` of them are also run through sequential
    ``GPT.generate`` for the token-identity gate and the speedup baseline.
    Both paths are timed warm (one warm engine request per distinct
    prefill bucket); the engine run is two waves so late arrivals really
    do join slots mid-decode.  Returns the leg dict."""
    import paddle_tpu as paddle
    from paddle_tpu.core import flags as _flags
    from paddle_tpu.models import GPTForCausalLM
    from paddle_tpu.profiler import counters
    from paddle_tpu.profiler import trace as rtrace
    from paddle_tpu.serving import LLMEngine
    from paddle_tpu.serving.engine import bucket_length

    paddle.seed(seed)
    model = GPTForCausalLM(cfg)
    model.eval()
    rng = np.random.RandomState(seed)
    S = cfg.max_seq_len
    n_verify = min(n_verify, n_requests)
    lens = [int(rng.randint(max(2, S // 16), S - max_new))
            for _ in range(n_requests)]
    prompts = [rng.randint(0, cfg.vocab_size, size=n).tolist()
               for n in lens]

    def seq_pass():
        return [np.asarray(model.generate(
            paddle.to_tensor(np.asarray([p])),
            max_new_tokens=max_new).numpy())[0]
            for p in prompts[:n_verify]]
    seq_pass()  # warm: one compiled generate program per prompt length
    t0 = time.perf_counter()
    seq_outs = seq_pass()
    seq_s = time.perf_counter() - t0

    eng = LLMEngine(model, max_slots=max_slots, max_seq_len=S,
                    min_bucket=min_bucket)
    # warm: one throwaway request per distinct prefill bucket (compiles
    # prefill + insert) plus the decode program
    warm = [rng.randint(0, cfg.vocab_size,
                        size=min(b, S - 3)).tolist()
            for b in sorted({bucket_length(n, min_bucket, S)
                             for n in lens})]
    for _ in eng.generate(warm, max_new_tokens=2):
        pass
    warmed_counts = {n: h.count for n, h in eng.hists.items()}
    # measured pass runs fully traced (head sampling = keep all): the leg
    # reports WHERE the latency tail lives (queue vs prefill vs decode),
    # not just that it exists.  The parity gates elsewhere prove tracing
    # adds zero syncs/retraces, so tracing the timed pass is honest.
    rtrace.clear()
    _flags.set_flags({"FLAGS_request_trace_sample": 1.0})
    before = counters.snapshot()
    t0 = time.perf_counter()
    try:
        half = n_requests // 2
        hs = [eng.add_request(p, max_new_tokens=max_new)
              for p in prompts[:half]]
        for _ in range(3):
            eng.step()  # wave 1 decodes; wave 2 arrives mid-flight
        hs += [eng.add_request(p, max_new_tokens=max_new)
               for p in prompts[half:]]
        while not all(h.is_finished for h in hs):
            eng.step()
    finally:
        _flags.set_flags({"FLAGS_request_trace_sample": 0.0})
    serve_s = time.perf_counter() - t0
    delta = counters.delta(before)
    trace_block = {"sample": 1.0,
                   "kept": len(rtrace.kept_ids()),
                   "stages": rtrace.stage_breakdown()}

    match = all(np.array_equal(h.output_ids(), s)
                for h, s in zip(hs[:n_verify], seq_outs))
    serve_tps = n_requests * max_new / max(serve_s, 1e-9)
    seq_tps = n_verify * max_new / max(seq_s, 1e-9)
    snap = eng.histogram_snapshot()
    leg = {"requests": n_requests,
           "max_new_tokens": max_new,
           "max_slots": max_slots,
           "decode_tokens_per_sec": round(serve_tps, 2),
           "sequential_tokens_per_sec": round(seq_tps, 2),
           "serve_speedup": round(serve_tps / max(seq_tps, 1e-9), 4),
           "outputs_match_generate": match,
           "steady_retraces": delta.get("serving.retraces", 0),
           "prefill_programs": eng.stats()["prefill_programs"],
           "ttft": _latency_ms(snap["serving.ttft_ns"]),
           "itl": _latency_ms(snap["serving.itl_ns"]),
           "queue_wait": _latency_ms(snap["serving.queue_wait_ns"]),
           "trace": trace_block}
    # the tail stats must cover the measured request set, not just warmup
    measured = snap["serving.ttft_ns"].count \
        - warmed_counts["serving.ttft_ns"]
    if measured < n_requests:
        raise AssertionError(
            f"serving leg: TTFT histogram covered {measured} measured "
            f"requests, expected {n_requests}")
    if trace_block["kept"] < n_requests:
        raise AssertionError(
            f"serving leg: only {trace_block['kept']} request traces kept "
            f"at sample=1, expected {n_requests}")
    if not match:
        raise AssertionError(
            "serving leg: engine output diverged from sequential "
            "GPT.generate")
    leg["devicetime"] = _sampled_devicetime(
        lambda: [None for _ in eng.generate(prompts[:4],
                                            max_new_tokens=8)])
    del eng, model
    return leg


def _run_paged_leg(cfg, n_requests=64, max_new=64, max_slots=8,
                   min_bucket=8, block_size=16, prefill_chunk=256,
                   n_verify=8, seed=0):
    """Paged KV cache vs the legacy slot arena at the SAME KV HBM budget.

    Leg 1 (capacity): a mixed-length request set served by the slot
    engine (``max_slots`` rows of ``S_max``) and by a paged engine whose
    block pool holds exactly the slot arena's token capacity
    (``max_slots * ceil(S/bs)`` blocks).  Because paged requests reserve
    only the blocks they can actually touch, the pool admits several
    requests per slot-arena-row-equivalent — gated at ≥2× peak
    concurrent admitted requests.  The first ``n_verify`` requests are
    verified token-identical to sequential ``GPT.generate`` on both
    engines, and decode tok/s parity is reported.

    Leg 2 (shared prefix): ``n_requests`` prompts sharing one
    system-prompt prefix, served sequentially enough to feed the prefix
    tree — reports TTFT p50/p95 and gates ``prefix_hits > 0`` with
    strictly fewer prefill-chunk launches than a no-cache twin."""
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTForCausalLM
    from paddle_tpu.profiler import counters
    from paddle_tpu.serving import LLMEngine
    from paddle_tpu.serving.engine import bucket_length
    from paddle_tpu.serving.kvcache import blocks_for_tokens

    paddle.seed(seed)
    model = GPTForCausalLM(cfg)
    model.eval()
    rng = np.random.RandomState(seed)
    S = cfg.max_seq_len
    n_verify = min(n_verify, n_requests)
    lo = max(2, S // 16)
    hi = max(lo + 1, S // 4 - max_new)
    lens = [int(rng.randint(lo, hi)) for _ in range(n_requests)]
    prompts = [rng.randint(0, cfg.vocab_size, size=n).tolist()
               for n in lens]
    refs = [np.asarray(model.generate(
        paddle.to_tensor(np.asarray([p])),
        max_new_tokens=max_new).numpy())[0] for p in prompts[:n_verify]]

    def serve(eng, ps):
        hs = [eng.add_request(p, max_new_tokens=max_new) for p in ps]
        peak = 0
        while not all(h.is_finished for h in hs):
            eng.step()
            peak = max(peak, eng.stats()["active"])
        return hs, peak

    # legacy slot arena: KV HBM = L x max_slots x S_max
    slot_eng = LLMEngine(model, max_slots=max_slots, max_seq_len=S,
                         min_bucket=min_bucket)
    warm = [rng.randint(0, cfg.vocab_size, size=min(b, S - 3)).tolist()
            for b in sorted({bucket_length(n, min_bucket, S)
                             for n in lens})]
    for _ in slot_eng.generate(warm, max_new_tokens=2):
        pass
    t0 = time.perf_counter()
    shs, slot_peak = serve(slot_eng, prompts)
    slot_s = time.perf_counter() - t0
    slot_tps = n_requests * max_new / max(slot_s, 1e-9)
    for h, r in zip(shs[:n_verify], refs):
        if not np.array_equal(h.output_ids(), r):
            raise AssertionError(
                "paged leg: slot-engine output diverged from generate")
    del slot_eng

    # paged twin at the SAME KV HBM: pool == the slot arena's tokens;
    # scheduling slots are host-side bookkeeping, so the admitted
    # concurrency is bounded by memory, not by rows
    n_blocks = max_slots * blocks_for_tokens(S, block_size) + 1
    peng = LLMEngine(model, max_slots=4 * max_slots, max_seq_len=S,
                     min_bucket=min_bucket, kv_layout="paged",
                     block_size=block_size, n_blocks=n_blocks,
                     prefill_chunk=prefill_chunk)
    # warm one request per power-of-two chunk bucket (+ the decode)
    b, pwarm = min_bucket, []
    while b <= peng.prefill_chunk:
        pwarm.append(rng.randint(0, cfg.vocab_size,
                                 size=min(b, S - 3)).tolist())
        b *= 2
    for _ in peng.generate(pwarm, max_new_tokens=2):
        pass
    pbefore = counters.snapshot()
    t0 = time.perf_counter()
    phs, paged_peak = serve(peng, prompts)
    paged_s = time.perf_counter() - t0
    pdelta = counters.delta(pbefore)
    paged_tps = n_requests * max_new / max(paged_s, 1e-9)
    for h, r in zip(phs[:n_verify], refs):
        if not np.array_equal(h.output_ids(), r):
            raise AssertionError(
                "paged leg: paged-engine output diverged from generate")
    capacity_ratio = paged_peak / max(1, slot_peak)
    if capacity_ratio < 2.0:
        raise AssertionError(
            f"paged leg: peak concurrency {paged_peak} vs slot "
            f"{slot_peak} = {capacity_ratio:.2f}x at the same KV HBM "
            "(want >= 2x)")

    # shared-system-prompt workload: TTFT tail + prefix-cache economics.
    # The first request prefills the system prompt; it is finished (and
    # donated to the tree) before the rest arrive, so every later
    # request shares the cached prefix.
    bs = block_size
    sys_len = max(bs, (S // 4 // bs) * bs)
    tail_len = max(2, min(bs, S - sys_len - max_new - 2))
    sysp = rng.randint(0, cfg.vocab_size, size=sys_len).tolist()
    shared = [sysp + rng.randint(0, cfg.vocab_size,
                                 size=tail_len).tolist()
              for _ in range(n_requests)]

    def serve_shared(eng):
        h0 = eng.add_request(shared[0], max_new_tokens=max_new)
        while not h0.is_finished:
            eng.step()
        hs = [eng.add_request(p, max_new_tokens=max_new)
              for p in shared[1:]]
        while not all(h.is_finished for h in hs):
            eng.step()

    nc_eng = LLMEngine(model, max_slots=4 * max_slots, max_seq_len=S,
                       min_bucket=min_bucket, kv_layout="paged",
                       block_size=block_size, n_blocks=n_blocks,
                       prefill_chunk=prefill_chunk, prefix_cache=False)
    ncbefore = counters.snapshot()
    serve_shared(nc_eng)
    nc_chunks = counters.delta(ncbefore).get("serving.kv.prefill_chunks",
                                             0)
    del nc_eng
    pc_eng = LLMEngine(model, max_slots=4 * max_slots, max_seq_len=S,
                       min_bucket=min_bucket, kv_layout="paged",
                       block_size=block_size, n_blocks=n_blocks,
                       prefill_chunk=prefill_chunk)
    pcbefore = counters.snapshot()
    t0 = time.perf_counter()
    serve_shared(pc_eng)
    shared_s = time.perf_counter() - t0
    pcdelta = counters.delta(pcbefore)
    pc_chunks = pcdelta.get("serving.kv.prefill_chunks", 0)
    pc_hits = pcdelta.get("serving.kv.prefix_hits", 0)
    if pc_hits < n_requests - 1:
        raise AssertionError(
            f"paged leg: shared-prefix workload scored {pc_hits} "
            f"prefix hits (want >= {n_requests - 1})")
    if not pc_chunks < nc_chunks:
        raise AssertionError(
            f"paged leg: prefix cache launched {pc_chunks} prefill "
            f"chunks vs {nc_chunks} without (want strictly fewer)")
    snap = pc_eng.histogram_snapshot()
    pstats = pc_eng.stats()
    leg = {"requests": n_requests,
           "max_new_tokens": max_new,
           "block_size": block_size,
           "n_blocks": n_blocks,
           "prefill_chunk": peng.prefill_chunk,
           "kv_hbm_slots_equiv": max_slots,
           "peak_concurrent_slot": slot_peak,
           "peak_concurrent_paged": paged_peak,
           "capacity_ratio": round(capacity_ratio, 3),
           "decode_tokens_per_sec_slot": round(slot_tps, 2),
           "decode_tokens_per_sec_paged": round(paged_tps, 2),
           "decode_parity": round(paged_tps / max(slot_tps, 1e-9), 4),
           "steady_retraces": pdelta.get("serving.retraces", 0),
           "outputs_match_generate": True,
           "shared_prefix": {
               "requests": n_requests,
               "system_prompt_tokens": sys_len,
               "prefix_hits": pc_hits,
               "prefix_hit_tokens": pcdelta.get(
                   "serving.kv.prefix_hit_tokens", 0),
               "prefill_chunks": pc_chunks,
               "prefill_chunks_nocache": nc_chunks,
               "wall_s": round(shared_s, 3),
               "ttft": _latency_ms(snap["serving.ttft_ns"]),
               "itl": _latency_ms(snap["serving.itl_ns"]),
               "block_occupancy_p95": round(
                   snap["serving.kv.block_occupancy"].percentile(95),
                   4)},
           "blocks_evicted": pstats["blocks_evicted"],
           "cow_copies": pstats["cow_copies"]}
    leg["devicetime"] = _sampled_devicetime(
        lambda: [None for _ in pc_eng.generate(prompts[:4],
                                               max_new_tokens=8)])
    del peng, pc_eng, model
    return leg


def _run_paged_q_leg(cfg, n_requests=64, max_new=64, max_slots=4,
                     min_bucket=8, block_size=16, prefill_chunk=256,
                     kv_dtype="int8", n_verify=4, seed=0):
    """Quantized-KV capacity leg: an ``kv_dtype`` paged engine vs the
    model-dtype paged baseline at the SAME KV HBM byte budget.

    The baseline pool is sized like the paged leg's
    (``max_slots * ceil(S/bs)`` blocks of the model dtype); the
    quantized pool gets ``floor(budget / quant_block_bytes)`` blocks
    where a quantized block costs 1 byte/value plus the per-token fp32
    scale rows (8 bytes per token across K and V).  Both engines serve
    the same memory-bound workload (identical-length prompts, scheduling
    slots ample, so admission is bounded by pool bytes alone) — gated at
    >= 2x peak concurrent admitted requests with zero steady retraces.
    Decode tok/s and TTFT/ITL are reported for both; the >=0.9x decode
    parity gate applies on TPU only (on CPU the dequant is extra VPU-less
    arithmetic, numbers informational).  Token identity of the quantized
    engine is gated in tests/ and scripts/check_counters.py on the tiny
    model; here the baseline engine is verified against ``generate`` and
    the quantized match count is reported."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.kernels.paged_attention import KV_DTYPES
    from paddle_tpu.models import GPTForCausalLM
    from paddle_tpu.profiler import counters
    from paddle_tpu.serving import LLMEngine
    from paddle_tpu.serving.kvcache import blocks_for_tokens

    paddle.seed(seed)
    model = GPTForCausalLM(cfg)
    model.eval()
    rng = np.random.RandomState(seed)
    S = cfg.max_seq_len
    L, nh = cfg.num_layers, cfg.num_heads
    hd = cfg.hidden_size // nh
    bs = block_size
    dt = jnp.dtype(cfg.dtype)
    adt = jnp.dtype(KV_DTYPES[kv_dtype])
    # the fixed byte budget: the baseline pool's K+V arena
    raw_block = 2 * L * bs * nh * hd * dt.itemsize
    q_block = 2 * L * bs * nh * hd * adt.itemsize + 2 * L * bs * 4
    n_blocks_raw = max_slots * blocks_for_tokens(S, bs) + 1
    budget = n_blocks_raw * raw_block
    n_blocks_q = int(budget // q_block)

    plen = max(2, S // 8)
    prompts = [rng.randint(0, cfg.vocab_size, size=plen).tolist()
               for _ in range(n_requests)]
    n_verify = min(n_verify, n_requests)
    refs = [np.asarray(model.generate(
        paddle.to_tensor(np.asarray([p])),
        max_new_tokens=max_new).numpy())[0] for p in prompts[:n_verify]]

    def engine(n_blocks, **kw):
        eng = LLMEngine(model, max_slots=n_requests, max_seq_len=S,
                        min_bucket=min_bucket, kv_layout="paged",
                        block_size=bs, n_blocks=n_blocks,
                        prefill_chunk=prefill_chunk, prefix_cache=False,
                        **kw)
        b, pwarm = min_bucket, []
        while b <= eng.prefill_chunk:
            pwarm.append(rng.randint(0, cfg.vocab_size,
                                     size=min(b, S - 3)).tolist())
            b *= 2
        for _ in eng.generate(pwarm, max_new_tokens=2):
            pass
        return eng

    def serve(eng):
        hs = [eng.add_request(p, max_new_tokens=max_new) for p in prompts]
        peak = 0
        t0 = time.perf_counter()
        while not all(h.is_finished for h in hs):
            eng.step()
            peak = max(peak, eng.stats()["active"])
        return hs, peak, time.perf_counter() - t0

    beng = engine(n_blocks_raw)
    bhs, raw_peak, raw_s = serve(beng)
    raw_tps = n_requests * max_new / max(raw_s, 1e-9)
    for h, r in zip(bhs[:n_verify], refs):
        if not np.array_equal(h.output_ids(), r):
            raise AssertionError(
                "paged_q leg: baseline paged output diverged from "
                "generate")
    raw_snap = beng.histogram_snapshot()
    del beng

    qeng = engine(n_blocks_q, kv_dtype=kv_dtype)
    qbefore = counters.snapshot()
    qhs, q_peak, q_s = serve(qeng)
    qdelta = counters.delta(qbefore)
    q_tps = n_requests * max_new / max(q_s, 1e-9)
    q_match = sum(int(np.array_equal(h.output_ids(), r))
                  for h, r in zip(qhs[:n_verify], refs))
    capacity_ratio = q_peak / max(1, raw_peak)
    if capacity_ratio < 2.0:
        raise AssertionError(
            f"paged_q leg: {kv_dtype} peak concurrency {q_peak} vs "
            f"{dt.name} {raw_peak} = {capacity_ratio:.2f}x at the same "
            "KV HBM byte budget (want >= 2x)")
    if qdelta.get("serving.retraces", 0):
        raise AssertionError(
            f"paged_q leg: {qdelta['serving.retraces']} steady retraces "
            "on the quantized engine (want 0)")
    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    decode_parity = q_tps / max(raw_tps, 1e-9)
    if on_tpu and decode_parity < 0.9:
        raise AssertionError(
            f"paged_q leg: quantized decode {q_tps:.1f} tok/s vs "
            f"baseline {raw_tps:.1f} = {decode_parity:.2f}x (want >= "
            "0.9x on TPU)")
    q_snap = qeng.histogram_snapshot()
    leg = {"kv_dtype": kv_dtype,
           "requests": n_requests,
           "max_new_tokens": max_new,
           "prompt_tokens": plen,
           "block_size": bs,
           "kv_hbm_budget_bytes": int(budget),
           "n_blocks_raw": n_blocks_raw,
           "n_blocks_quant": n_blocks_q,
           "block_bytes_raw": raw_block,
           "block_bytes_quant": q_block,
           "arena_bytes_quant": counters.get(
               "serving.kv.quant.arena_bytes"),
           "bytes_saved_vs_same_blocks": counters.get(
               "serving.kv.quant.bytes_saved"),
           "peak_concurrent_raw": raw_peak,
           "peak_concurrent_quant": q_peak,
           "capacity_ratio": round(capacity_ratio, 3),
           "decode_tokens_per_sec_raw": round(raw_tps, 2),
           "decode_tokens_per_sec_quant": round(q_tps, 2),
           "decode_parity": round(decode_parity, 4),
           "steady_retraces": qdelta.get("serving.retraces", 0),
           "quant_tokens": qdelta.get("serving.kv.quant.prefill_tokens",
                                      0)
           + qdelta.get("serving.kv.quant.decode_tokens", 0),
           "verified_match_raw": n_verify,
           "verified_match_quant": f"{q_match}/{n_verify}",
           "ttft_raw": _latency_ms(raw_snap["serving.ttft_ns"]),
           "ttft_quant": _latency_ms(q_snap["serving.ttft_ns"]),
           "itl_raw": _latency_ms(raw_snap["serving.itl_ns"]),
           "itl_quant": _latency_ms(q_snap["serving.itl_ns"])}
    del qeng, model
    return leg


def _run_spec_leg(n_requests=16, max_new=32, max_slots=4, min_bucket=8,
                  block_size=16, prefill_chunk=64, spec_k=4, hidden=512,
                  layers=12, draft_layers=1, vocab=512, seq_len=256,
                  seed=0, min_speedup=1.3):
    """Speculative-decoding leg: draft/verify engine vs the non-spec
    paged baseline on the same greedy workload.

    The model pair is ALIGNED by construction: both share the embedding /
    final-norm weights and every transformer block's matmul weights are
    zeroed (a zero block contributes nothing to the residual stream but
    still costs its full matmul FLOPs/bytes), so draft and target emit
    the same greedy chain and acceptance sits at ~1.0 — the leg measures
    the MACHINERY's ceiling (one [B, K+1] verify amortizes the target's
    weight sweep over up to K+1 tokens) rather than any particular
    trained draft's acceptance.  The target is many zeroed layers deep so
    its weight sweep dominates; the draft is ``draft_layers`` of the same
    width.

    Gates: speculative greedy output token-identical to the baseline
    engine; zero steady-state retraces over the measured window;
    ``accepted + rejected == drafted``; net decode tok/s >=
    ``min_speedup`` x the baseline (the CPU-fallback gate — the weight
    sweep is bandwidth-bound on CPU exactly as on TPU)."""
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.profiler import counters
    from paddle_tpu.serving import LLMEngine
    from paddle_tpu.serving.kvcache import blocks_for_tokens

    def build(n_layers, seed_):
        cfg = GPTConfig(vocab_size=vocab, hidden_size=hidden,
                        num_layers=n_layers, num_heads=8,
                        max_seq_len=seq_len, use_rope=True,
                        use_flash_attention=False, dtype="float32")
        paddle.seed(seed_)
        m = GPTForCausalLM(cfg)
        m.eval()
        for n in ("qkv_w", "qkv_b", "proj_w", "proj_b",
                  "fc1_w", "fc1_b", "fc2_w", "fc2_b"):
            p = getattr(m, n)
            p._data = jnp.zeros_like(p._data)
        return m

    target = build(layers, seed)
    draft = build(draft_layers, seed + 1)
    for n in ("wte", "lnf_w", "lnf_b"):
        getattr(draft, n)._data = getattr(target, n)._data

    rng = np.random.RandomState(seed)
    plen = max(2, seq_len // 8)
    prompts = [rng.randint(0, vocab, size=plen).tolist()
               for _ in range(n_requests)]
    n_blocks = 2 * max_slots * blocks_for_tokens(seq_len, block_size) + 1

    def engine(**kw):
        eng = LLMEngine(target, max_slots=max_slots, max_seq_len=seq_len,
                        min_bucket=min_bucket, kv_layout="paged",
                        block_size=block_size, n_blocks=n_blocks,
                        prefill_chunk=prefill_chunk, prefix_cache=False,
                        **kw)
        b, pwarm = min_bucket, []
        while b <= eng.prefill_chunk:
            pwarm.append(rng.randint(0, vocab,
                                     size=min(b, seq_len - 3)).tolist())
            b *= 2
        for _ in eng.generate(pwarm, max_new_tokens=2):
            pass
        return eng

    def serve(eng):
        hs = [eng.add_request(p, max_new_tokens=max_new, seed=i)
              for i, p in enumerate(prompts)]
        t0 = time.perf_counter()
        while not all(h.is_finished for h in hs):
            eng.step()
        return hs, time.perf_counter() - t0

    beng = engine()
    bhs, base_s = serve(beng)
    base_tps = n_requests * max_new / max(base_s, 1e-9)
    base_snap = beng.histogram_snapshot()
    del beng

    seng = engine(draft_model=draft, spec_k=spec_k)
    before = counters.snapshot()
    shs, spec_s = serve(seng)
    delta = counters.delta(before)
    spec_tps = n_requests * max_new / max(spec_s, 1e-9)
    for b, s in zip(bhs, shs):
        if b.tokens != s.tokens:
            raise AssertionError(
                "spec leg: speculative greedy output diverged from the "
                "non-speculative paged engine")
    if delta.get("serving.retraces", 0):
        raise AssertionError(
            f"spec leg: {delta['serving.retraces']} steady retraces on "
            "the speculative engine (want 0)")
    drafted = delta.get("serving.spec.drafted", 0)
    accepted = delta.get("serving.spec.accepted", 0)
    rejected = delta.get("serving.spec.rejected", 0)
    if accepted + rejected != drafted:
        raise AssertionError(
            f"spec leg: accepted {accepted} + rejected {rejected} != "
            f"drafted {drafted}")
    speedup = spec_tps / max(base_tps, 1e-9)
    if speedup < min_speedup:
        raise AssertionError(
            f"spec leg: speculative decode {spec_tps:.1f} tok/s vs "
            f"baseline {base_tps:.1f} = {speedup:.2f}x (want >= "
            f"{min_speedup}x)")
    spec_snap = seng.histogram_snapshot()
    st = seng.stats()
    leg = {"spec_k": spec_k,
           "requests": n_requests,
           "max_new_tokens": max_new,
           "prompt_tokens": plen,
           "target_layers": layers,
           "draft_layers": draft_layers,
           "hidden": hidden,
           "drafted": drafted,
           "accepted": accepted,
           "rejected": rejected,
           "acceptance_rate": round(accepted / max(1, drafted), 4),
           "acceptance_ema": st["spec_acceptance_ema"],
           "yield_ema": round(st["spec_yield_ema"], 3),
           "verify_steps": delta.get("serving.spec.verify_steps", 0),
           "draft_steps": delta.get("serving.spec.draft_steps", 0),
           "rollback_blocks": delta.get("serving.spec.rollback_blocks",
                                        0),
           "steady_retraces": delta.get("serving.retraces", 0),
           "decode_tokens_per_sec_base": round(base_tps, 2),
           "decode_tokens_per_sec_spec": round(spec_tps, 2),
           "spec_speedup": round(speedup, 4),
           "ttft_base": _latency_ms(base_snap["serving.ttft_ns"]),
           "ttft_spec": _latency_ms(spec_snap["serving.ttft_ns"]),
           "itl_base": _latency_ms(base_snap["serving.itl_ns"]),
           "itl_spec": _latency_ms(spec_snap["serving.itl_ns"])}
    leg["devicetime"] = _sampled_devicetime(
        lambda: [None for _ in seng.generate(prompts[:4],
                                             max_new_tokens=8)])
    del seng, target, draft
    return leg


def _run_fleet_leg(cfg, replicas=2, n_requests=8, max_new=32, max_slots=4,
                   min_bucket=8, seed=0):
    """Elastic-fleet leg: the same seeded request set through a
    multi-replica ``ServingFleet`` twice — clean, then with one replica
    killed mid-decode (deterministic ``replica_crash`` on the first
    request).  Reports aggregate decode tokens/s for both runs and the
    churn retention fraction, and gates the durability invariants: zero
    lost requests, respawns == injected kills, and the churn output
    token-identical to the clean run (same seeds → same streams, replayed
    across the respawn)."""
    import urllib.request

    import paddle_tpu as paddle
    from paddle_tpu.core import flags as _flags
    from paddle_tpu.models import GPTForCausalLM
    from paddle_tpu.profiler import counters
    from paddle_tpu.profiler import trace as rtrace
    from paddle_tpu.profiler.ops import OpsServer
    from paddle_tpu.resilience import faultinject
    from paddle_tpu.serving import ServingFleet

    paddle.seed(seed)
    model = GPTForCausalLM(cfg)
    model.eval()
    rng = np.random.RandomState(seed)
    S = cfg.max_seq_len
    lens = [int(rng.randint(max(2, S // 16), S - max_new))
            for _ in range(n_requests)]
    prompts = [rng.randint(0, cfg.vocab_size, size=n).tolist()
               for n in lens]
    seeds = list(range(100, 100 + n_requests))

    fleet = ServingFleet(model, replicas=replicas, max_slots=max_slots,
                         max_seq_len=S, min_bucket=min_bucket,
                         threaded=False, warm_buckets=lens)

    def run_pass(kill=False):
        before = counters.snapshot()
        t0 = time.perf_counter()
        hs = [fleet.submit(p, max_new_tokens=max_new, seed=s)
              for p, s in zip(prompts, seeds)]
        if kill:
            with faultinject.fault_schedule(
                    f"replica_crash@{hs[0].rid}"):
                fleet.join(hs)
        else:
            fleet.join(hs)
        dt = time.perf_counter() - t0
        return hs, dt, counters.delta(before)

    run_pass()  # warm timing pass (programs already compiled at spawn)
    # both measured passes run traced: the churn pass's respawned request
    # keeps ONE trace_id across replicas, so the breakdown sees the full
    # redispatch story, not two half-requests
    rtrace.clear()
    _flags.set_flags({"FLAGS_request_trace_sample": 1.0})
    try:
        clean_hs, clean_s, clean_d = run_pass()
        churn_hs, churn_s, churn_d = run_pass(kill=True)
    finally:
        _flags.set_flags({"FLAGS_request_trace_sample": 0.0})
    # fleet-wide latency tail: replica histograms merged by the router
    # (dead replicas included — their delivered latency counts)
    agg = fleet.router.aggregate_histograms(fleet._replicas)
    obs = fleet.router.observability_summary(fleet._replicas)
    # ops-endpoint smoke: the live process plane serves this very fleet
    # over HTTP while it is still up (ephemeral port, stdlib client)
    with OpsServer(fleet=fleet) as srv:
        with urllib.request.urlopen(srv.url("/healthz"), timeout=10) as r:
            ops_health = json.loads(r.read())
        with urllib.request.urlopen(srv.url("/traces"), timeout=10) as r:
            ops_traces = json.loads(r.read())
    fleet.drain()

    match = all(c.finish_reason == "length" and k.finish_reason == "length"
                and c.tokens == k.tokens
                for c, k in zip(clean_hs, churn_hs))
    decode_tokens = n_requests * max_new
    clean_tps = decode_tokens / max(clean_s, 1e-9)
    churn_tps = decode_tokens / max(churn_s, 1e-9)
    leg = {"replicas": replicas,
           "requests": n_requests,
           "max_new_tokens": max_new,
           "decode_tokens_per_sec": round(clean_tps, 2),
           "churn_decode_tokens_per_sec": round(churn_tps, 2),
           "churn_retention": round(churn_tps / max(clean_tps, 1e-9), 4),
           "respawns": churn_d.get("serving.fleet.respawns", 0),
           "retried": churn_d.get("serving.fleet.retried", 0),
           "lost": churn_d.get("serving.fleet.lost", 0),
           "replayed_tokens": churn_d.get("serving.fleet.replayed_tokens",
                                          0),
           "steady_retraces": clean_d.get("serving.retraces", 0),
           "outputs_match_clean": match,
           "ttft": _latency_ms(agg["serving.ttft_ns"]),
           "itl": _latency_ms(agg["serving.itl_ns"]),
           "queue_wait": _latency_ms(agg["serving.queue_wait_ns"]),
           "trace": {"kept": obs["traces_kept"],
                     "stages": obs["stage_breakdown"]},
           "ops": {"healthz": ops_health.get("status"),
                   "alive": (ops_health.get("fleet") or {}).get("alive"),
                   "traces_kept": ops_traces.get("count")}}
    if (not match or leg["lost"] != 0 or leg["respawns"] != 1
            or leg["retried"] < 1 or leg["steady_retraces"] != 0):
        raise AssertionError(
            f"fleet leg broke the durability invariants: {leg}")
    if ops_health.get("status") != "ok" or not ops_traces.get("count"):
        raise AssertionError(
            f"fleet leg: live ops endpoint unhealthy or trace-blind: "
            f"{leg['ops']}")
    del fleet, model
    return leg


def _run_multitenant_leg(cfg, replicas=2, tenants=6, adapter_slots=4,
                         rank=8, n_requests=12, max_new=32, max_slots=4,
                         min_bucket=8, block_size=16, prefill_chunk=None,
                         seed=0):
    """Multi-tenant LoRA serving leg: ``tenants`` adapters through a
    ``replicas``-replica fleet whose per-replica AdapterArena holds only
    ``adapter_slots`` of them, so cold tenants page in on demand and the
    LRU evicts idle ones — many model variants at the HBM cost of a few.
    Two measured passes: FAIR (tenants round-robin with base rows mixed
    in) and NOISY (tenant 0 floods the fleet while the others get one
    request each, plus an injected ``adapter_load_drop`` on one
    admission).  Reports decode tokens/s for both, per-tenant-bucket
    TTFT/ITL tails from the router-merged histograms, the noisy pass's
    flood-bucket ITL-p95 skew, arena traffic (loads / evictions /
    resident / bytes) and the router's tenant-affinity wins; gates zero
    lost requests, the dropped load recovering to a finished
    token-identical request, paging genuinely exercised (loads AND
    evictions move), and the fair pass token-identical across repeats
    with ZERO steady retraces — one compiled decode program serves every
    tenant mix."""
    import zlib

    import paddle_tpu as paddle
    from paddle_tpu.models import GPTForCausalLM
    from paddle_tpu.profiler import counters
    from paddle_tpu.resilience import faultinject
    from paddle_tpu.serving import ServingFleet
    from paddle_tpu.serving.adapters import random_lora_factors

    paddle.seed(seed)
    model = GPTForCausalLM(cfg)
    model.eval()
    rng = np.random.RandomState(seed)
    S = cfg.max_seq_len
    lens = [int(rng.randint(max(2, S // 16), S - max_new))
            for _ in range(n_requests)]
    prompts = [rng.randint(0, cfg.vocab_size, size=n).tolist()
               for n in lens]
    seeds = list(range(100, 100 + n_requests))
    names = [f"tenant{i}" for i in range(tenants)]
    # fair mix: tenants round-robin, every (tenants+1)-th row base; noisy
    # mix: tenant 0 floods, every other tenant trickles one request, and
    # the LAST row is a tenant no pass has touched — its admission MUST
    # page in, so the adapter_load_drop scheduled on it always fires
    cold = "coldspare"
    fair = [None if i % (tenants + 1) == tenants
            else names[i % (tenants + 1)] for i in range(n_requests)]
    noisy = ([names[0]] * (n_requests - tenants)) + names[1:] + [cold]

    fleet = ServingFleet(model, replicas=replicas, max_slots=max_slots,
                         max_seq_len=S, min_bucket=min_bucket,
                         threaded=False, warm_buckets=lens,
                         kv_layout="paged", block_size=block_size,
                         prefill_chunk=prefill_chunk,
                         adapter_slots=adapter_slots, adapter_rank=rank)
    for i, t in enumerate(names + [cold]):
        fleet.register_adapter(
            t, random_lora_factors(cfg, rank, seed=10 + i, scale=0.05))

    def run_pass(mix, drop_on_last=False):
        before = counters.snapshot()
        t0 = time.perf_counter()
        hs = [fleet.submit(p, max_new_tokens=max_new, seed=s, adapter=t)
              for p, s, t in zip(prompts, seeds, mix)]
        if drop_on_last:
            # the engine-side load fires at admission inside pump(), so
            # scheduling after submit still intercepts it
            with faultinject.fault_schedule(
                    f"adapter_load_drop@{hs[-1]._er.rid}"):
                fleet.join(hs)
                fired = [s for s, _ in faultinject.fired]
        else:
            fleet.join(hs)
            fired = []
        dt = time.perf_counter() - t0
        return hs, dt, counters.delta(before), fired

    run_pass(fair)  # warm pass: programs compiled, tenants paged once
    warm_hs, _, _, _ = run_pass(fair)  # identity reference (same seeds)
    fair_hs, fair_s, fair_d, _ = run_pass(fair)
    hist_mark = fleet.router.aggregate_histograms(fleet._replicas)
    noisy_hs, noisy_s, noisy_d, fired = run_pass(noisy, drop_on_last=True)
    agg = fleet.router.aggregate_histograms(fleet._replicas)
    stats = fleet.stats()
    fleet.drain()

    match = all(f.finish_reason == "length" and f.tokens == w.tokens
                for f, w in zip(fair_hs, warm_hs))
    drop_ok = (noisy_hs[-1].finish_reason == "length"
               and "adapter_load_drop" in fired)
    # per-tenant-bucket tails (cumulative) + the noisy pass's windowed
    # flood-bucket skew: flood p95 vs the median p95 of the other buckets
    n_buckets = fleet._replicas[0].engine.tenant_buckets
    flood = f"t{zlib.crc32(names[0].encode()) % n_buckets}"
    per_tenant = {
        name.rsplit(".", 1)[-1]: _latency_ms(h)
        for name, h in sorted(agg.items())
        if name.startswith("serving.itl_ns.tenant.")}
    win, skew = {}, None
    for name, h in agg.items():
        if name.startswith("serving.itl_ns.tenant."):
            prev = hist_mark.get(name)
            d = h.delta(prev) if prev is not None else h
            if d.count >= 8:
                win[name.rsplit(".", 1)[-1]] = d.percentile(95)
    others = sorted(v for k, v in win.items() if k != flood)
    if flood in win and others:
        skew = round(win[flood] / max(others[len(others) // 2], 1e-9), 3)
    ad = stats["adapters"]
    decode_tokens = n_requests * max_new
    fair_tps = decode_tokens / max(fair_s, 1e-9)
    noisy_tps = decode_tokens / max(noisy_s, 1e-9)
    leg = {"replicas": replicas,
           "tenants": tenants,
           "adapter_slots_per_replica": adapter_slots,
           "adapter_rank": rank,
           "requests": n_requests,
           "max_new_tokens": max_new,
           "decode_tokens_per_sec": round(fair_tps, 2),
           "noisy_decode_tokens_per_sec": round(noisy_tps, 2),
           "tenants_per_slot": round(tenants / adapter_slots, 2),
           "arena_bytes": ad["arena_bytes"],
           "resident": ad["resident"],
           "loads": ad["loads"],
           "evictions": ad["evictions"],
           "exhausted_defers": ad["exhausted"],
           "load_drops": ad["load_drops"],
           "adapter_routed": ad["routed"],
           "steady_retraces": fair_d.get("serving.retraces", 0),
           "outputs_match_warm": match,
           "noisy_itl_p95_skew": skew,
           "ttft": _latency_ms(agg["serving.ttft_ns"]),
           "itl": _latency_ms(agg["serving.itl_ns"]),
           "per_tenant_itl": per_tenant}
    leg["lost"] = (fair_d.get("serving.fleet.lost", 0)
                   + noisy_d.get("serving.fleet.lost", 0))
    if (not match or not drop_ok or leg["steady_retraces"] != 0
            or leg["lost"] != 0 or leg["loads"] < tenants
            or leg["evictions"] < 1):
        raise AssertionError(
            f"multitenant leg broke the adapter-serving invariants: {leg}")
    del fleet, model
    return leg


def _run_disagg_leg(cfg, n_long=6, n_short=18, max_new=16, max_slots=None,
                    min_bucket=8, block_size=8, prefill_chunk=16,
                    min_speedup=1.3, seed=0):
    """Disaggregated prefill/decode leg: the same mixed long/short
    request set through a 2-replica unified paged fleet and a 1+1
    prefill/decode split at EQUAL replica count.  On the split, every
    prompt prefills on the prefill replica and hands its KV to the
    decode replica by block-granular migration, so long-prompt prefill
    chunks stop interleaving with the decode iterations of streams
    already emitting tokens — the classic interference that owns the
    unified fleet's p95 inter-token latency under mixed traffic.

    Gates: disagg p95 ITL beats unified by >= ``min_speedup`` (the
    headline number), disagg output token-identical to unified, every
    request migrated exactly once, zero steady retraces on BOTH roles in
    BOTH modes (the one-decode-program economics survive the split), and
    a churn pass with a migration severed mid-flight (``kv_migrate_drop``)
    plus a replica killed mid-stream: zero lost requests, output
    token-identical to the clean disagg pass."""
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTForCausalLM
    from paddle_tpu.profiler import counters, metrics
    from paddle_tpu.resilience import faultinject
    from paddle_tpu.serving import ServingFleet

    paddle.seed(seed)
    model = GPTForCausalLM(cfg)
    model.eval()
    rng = np.random.RandomState(seed)
    S = cfg.max_seq_len
    long_lens = [int(rng.randint(int(S * 0.7), S - max_new))
                 for _ in range(n_long)]
    short_lens = [int(rng.randint(4, max(5, S // 8)))
                  for _ in range(n_short)]
    # interleave so short streams are mid-decode while long prefills
    # arrive — the interference the split is supposed to remove
    lens = []
    si = iter(short_lens)
    ratio = max(1, n_short // n_long)
    for n in long_lens:
        lens.extend(itertools.islice(si, ratio))
        lens.append(n)
    lens.extend(si)
    prompts = [rng.randint(0, cfg.vocab_size, size=n).tolist()
               for n in lens]
    # the warm pass runs DISJOINT prompts of the same lengths: it
    # compiles every program (prefill buckets, decode, the migration
    # gather) without seeding the prefix trees with the measured
    # prompts — a warm-pass prefix hit would erase the very prefill
    # work whose interference this leg measures
    warm_prompts = [rng.randint(0, cfg.vocab_size, size=n).tolist()
                    for n in lens]
    seeds = list(range(100, 100 + len(prompts)))
    if max_slots is None:
        # slots cover the whole burst on every replica: the comparison
        # isolates prefill/decode interference, not slot queueing (the
        # decode side of the split hosts ALL streams at once)
        max_slots = len(prompts)

    def build(prefill_replicas):
        # threaded: each replica gets its own scheduler thread, so the
        # split actually removes interference — a single shared loop
        # would serialize prefill chunks with decode steps regardless
        # of role assignment
        return ServingFleet(
            model, replicas=2, prefill_replicas=prefill_replicas,
            max_slots=max_slots, max_seq_len=S, min_bucket=min_bucket,
            threaded=True, kv_layout="paged", block_size=block_size,
            n_blocks=max(128, 4 * S // block_size * max_slots),
            prefill_chunk=prefill_chunk, warm_buckets=lens,
            max_retries=2)

    def run_pass(fleet, schedule=None, which=None):
        before = counters.snapshot()
        t0 = time.perf_counter()
        hs = [fleet.submit(p, max_new_tokens=max_new, seed=s)
              for p, s in zip(which if which is not None else prompts,
                              seeds)]
        if schedule:
            with faultinject.fault_schedule(schedule):
                fleet.join(hs)
        else:
            fleet.join(hs)
        dt = time.perf_counter() - t0
        return hs, dt, counters.delta(before)

    def measure(prefill_replicas, schedule=None, rounds=1):
        fleet = build(prefill_replicas)
        # warm pass (disjoint prompts): compiles the migrate program too
        run_pass(fleet, which=warm_prompts)
        # fresh per-engine histograms so the fleet percentiles below see
        # ONLY the measured rounds (warmup + warm-pass latency excluded)
        for rep in fleet._replicas:
            rep.engine.hists = {
                n: metrics.Histogram(n, h.unit)
                for n, h in rep.engine.hists.items()}
        before = counters.snapshot()
        hs = d1 = None
        total_s = 0.0
        for r in range(rounds):
            if r:
                # later rounds stay prefill-cold: drop the prefix blocks
                # the previous round donated, or every repeat would be a
                # prefix hit and skip the very work being measured
                for rep in fleet._replicas:
                    if rep.engine.prefix is not None:
                        rep.engine.prefix.clear()
            rhs, dt, d = run_pass(fleet, schedule=schedule)
            total_s += dt
            if hs is None:
                hs, d1 = rhs, d
            elif any(a.tokens != b.tokens for a, b in zip(rhs, hs)):
                raise AssertionError(
                    "disagg leg: identical seeds diverged across "
                    "measured rounds")
        d = counters.delta(before)
        # block economics come from the cold first round; retrace /
        # loss / migration-count gates cover every round
        d["serving.fleet.migrate.blocks_copied"] = d1.get(
            "serving.fleet.migrate.blocks_copied", 0)
        d["serving.fleet.migrate.blocks_shared"] = d1.get(
            "serving.fleet.migrate.blocks_shared", 0)
        agg = fleet.router.aggregate_histograms(fleet._replicas)
        roles = fleet.stats()["roles"]
        fleet.drain()
        return hs, total_s, d, agg, roles

    rounds = 3
    uni_hs, uni_s, uni_d, uni_agg, _ = measure(0, rounds=rounds)
    dis_hs, dis_s, dis_d, dis_agg, roles = measure(1, rounds=rounds)
    match = all(u.finish_reason == "length" and v.finish_reason == "length"
                and u.tokens == v.tokens
                for u, v in zip(uni_hs, dis_hs))
    # churn: one migration severed between export and adopt plus one
    # replica crash while hand-offs are in flight — replay must deliver
    # the identical streams with nothing lost
    # rids count per-fleet: the churn fleet's warm pass consumes
    # 0..len-1, so the measured pass starts at rid == len(prompts)
    churn_hs, _, churn_d, _, _ = measure(
        1, schedule=(f"kv_migrate_drop@{len(prompts)}"
                     f",replica_crash@{len(prompts) + 1}"))
    churn_match = all(v.finish_reason == "length" and c.tokens == v.tokens
                      for c, v in zip(churn_hs, dis_hs))
    uni_itl = _latency_ms(uni_agg["serving.itl_ns"])
    dis_itl = _latency_ms(dis_agg["serving.itl_ns"])
    speedup = uni_itl["p95_ms"] / max(dis_itl["p95_ms"], 1e-9)
    decode_tokens = len(prompts) * max_new * rounds
    leg = {"replicas": 2,
           "roles": roles,
           "requests": len(prompts),
           "measured_rounds": rounds,
           "long_prompts": n_long,
           "max_new_tokens": max_new,
           "unified_itl": uni_itl,
           "disagg_itl": dis_itl,
           "itl_p95_speedup": round(speedup, 4),
           "unified_ttft": _latency_ms(uni_agg["serving.ttft_ns"]),
           "disagg_ttft": _latency_ms(dis_agg["serving.ttft_ns"]),
           "unified_decode_tokens_per_sec":
               round(decode_tokens / max(uni_s, 1e-9), 2),
           "disagg_decode_tokens_per_sec":
               round(decode_tokens / max(dis_s, 1e-9), 2),
           "migrated": dis_d.get("serving.fleet.migrate.requests", 0),
           "blocks_copied":
               dis_d.get("serving.fleet.migrate.blocks_copied", 0),
           "blocks_shared":
               dis_d.get("serving.fleet.migrate.blocks_shared", 0),
           "migrate_deferred":
               dis_d.get("serving.fleet.migrate.deferred", 0),
           "steady_retraces_unified": uni_d.get("serving.retraces", 0),
           "steady_retraces_disagg": dis_d.get("serving.retraces", 0),
           "outputs_match_unified": match,
           "churn": {
               "dropped": churn_d.get("serving.fleet.migrate.dropped", 0),
               "deaths": churn_d.get("serving.fleet.replica_deaths", 0),
               "retried": churn_d.get("serving.fleet.retried", 0),
               "lost": churn_d.get("serving.fleet.lost", 0),
               "outputs_match_clean": churn_match}}
    if (not match or leg["migrated"] != len(prompts) * rounds
            or leg["steady_retraces_unified"] != 0
            or leg["steady_retraces_disagg"] != 0
            or uni_d.get("serving.fleet.lost", 0) != 0
            or dis_d.get("serving.fleet.lost", 0) != 0):
        raise AssertionError(
            f"disagg leg broke the migration invariants: {leg}")
    if (not churn_match or leg["churn"]["lost"] != 0
            or leg["churn"]["dropped"] < 1 or leg["churn"]["deaths"] < 1):
        raise AssertionError(
            f"disagg leg churn pass broke durability: {leg}")
    if speedup < min_speedup:
        raise AssertionError(
            f"disagg p95 ITL speedup {speedup:.3f}x below the "
            f"{min_speedup:.2f}x floor: {leg}")
    del model
    return leg


def _run_tiered_leg(cfg, n_sessions=24, max_new=64, max_slots=8,
                    min_bucket=8, block_size=16, prefill_chunk=256,
                    n_verify=4, seed=0, min_retention=0.5):
    """Host-RAM KV tier under 2x/4x oversubscribed device KV.

    Two-pass session traffic (every prompt queried twice — the second
    visit wants its first visit's KV back) served on identical prompts by
    three paged engines: a base whose block pool holds the whole working
    set, and two whose pools are cut to 1/2 and 1/4 of it with a pinned
    host tier sized to cover the difference.  Under oversubscription the
    radix tree's cold leaves spill to host buffers instead of being
    freed, and pass 2 restores them instead of re-prefilling.  Gates:
    token identity to sequential ``generate`` on every engine, every
    request reaching length/eos (zero sheds/errors under pressure),
    spill AND restore traffic actually flowing at 2x, and 2x decode
    tok/s >= ``min_retention`` of the base.  A 2-replica tiered fleet
    then replays the same traffic, gating prefix-affinity routing wins
    (``serving.fleet.prefix_routed`` — the router prices host-resident
    prefixes too) and the zero-lost / zero-shed invariants."""
    import paddle_tpu as paddle
    from paddle_tpu.models import GPTForCausalLM
    from paddle_tpu.profiler import counters
    from paddle_tpu.serving import LLMEngine, ServingFleet
    from paddle_tpu.serving.kvcache import blocks_for_tokens

    paddle.seed(seed)
    model = GPTForCausalLM(cfg)
    model.eval()
    rng = np.random.RandomState(seed)
    S = cfg.max_seq_len
    bs = block_size
    n_verify = min(n_verify, n_sessions)
    lo = max(2, S // 16)
    hi = max(lo + 1, S // 8)
    lens = [int(rng.randint(lo, hi)) for _ in range(n_sessions)]
    sessions = [rng.randint(0, cfg.vocab_size, size=n).tolist()
                for n in lens]
    refs = [np.asarray(model.generate(
        paddle.to_tensor(np.asarray([p])),
        max_new_tokens=max_new).numpy())[0] for p in sessions[:n_verify]]

    # the device working set: every session's full sequence resident
    demand = sum(blocks_for_tokens(n + max_new, bs) for n in lens)
    per_req = blocks_for_tokens(max(lens) + max_new, bs)
    nb_base = demand + max_slots + 1
    nb_2x = max(demand // 2, per_req + 2) + 1
    nb_4x = max(demand // 4, per_req + 2) + 1

    def build(n_blocks, host_blocks):
        eng = LLMEngine(model, max_slots=max_slots, max_seq_len=S,
                        min_bucket=min_bucket, kv_layout="paged",
                        block_size=bs, n_blocks=n_blocks,
                        prefill_chunk=prefill_chunk,
                        host_kv_blocks=host_blocks)
        # warm one request per power-of-two chunk bucket (+ the decode)
        b, pw = min_bucket, []
        while b <= eng.prefill_chunk:
            pw.append(rng.randint(0, cfg.vocab_size,
                                  size=min(b, S - 3)).tolist())
            b *= 2
        for _ in eng.generate(pw, max_new_tokens=2):
            pass
        if host_blocks:
            # compile the spill/restore programs too: demote the warm
            # chains to the host tier, then touch one so it pages back
            with eng._cond:
                eng._spill_cold(n_blocks)
            for _ in eng.generate([pw[-1]], max_new_tokens=2):
                pass
        eng.prefix.clear()  # measured passes start from a cold tree
        return eng

    def serve(eng, tag):
        before = counters.snapshot()
        t0 = time.perf_counter()
        passes = []
        for _ in range(2):
            hs = [eng.add_request(p, max_new_tokens=max_new)
                  for p in sessions]
            while not all(h.is_finished for h in hs):
                eng.step()
            passes.append(hs)
        wall = time.perf_counter() - t0
        d = counters.delta(before)
        for hs in passes:
            for h in hs:
                if h.finish_reason not in ("length", "eos"):
                    raise AssertionError(
                        f"tiered leg[{tag}]: request finished "
                        f"{h.finish_reason!r} under oversubscription")
            for h, r in zip(hs[:n_verify], refs):
                if not np.array_equal(h.output_ids(), r):
                    raise AssertionError(
                        f"tiered leg[{tag}]: output diverged from "
                        "sequential generate")
        sheds = sum(d.get(k, 0) for k in ("serving.fleet.shed",
                                          "serving.deadline_expired",
                                          "serving.request_errors"))
        tps = 2 * n_sessions * max_new / max(wall, 1e-9)
        return tps, d, sheds

    base = build(nb_base, 0)
    tps_base, _, sheds_base = serve(base, "base")
    del base
    e2x = build(nb_2x, demand)
    tps_2x, d2, sheds_2x = serve(e2x, "2x")
    del e2x
    e4x = build(nb_4x, demand)
    tps_4x, d4, sheds_4x = serve(e4x, "4x")
    del e4x

    # fleet-global prefix economy: the same two-pass traffic through a
    # 2-replica tiered fleet — the router's cost model must keep routing
    # each session's second visit back to the replica holding its prefix
    # (device- or host-resident, restore cost priced in)
    fbefore = counters.snapshot()
    fleet = ServingFleet(model, replicas=2, threaded=False,
                         max_slots=max_slots, max_seq_len=S,
                         min_bucket=min_bucket, kv_layout="paged",
                         block_size=bs, n_blocks=nb_2x,
                         prefill_chunk=prefill_chunk,
                         host_kv_blocks=demand,
                         queue_size=2 * n_sessions + 4)
    for _ in range(2):
        fhs = [fleet.submit(p, max_new_tokens=max_new) for p in sessions]
        fleet.join(fhs)
        for h in fhs:
            if h.finish_reason not in ("length", "eos"):
                raise AssertionError(
                    f"tiered leg[fleet]: request finished "
                    f"{h.finish_reason!r}")
    fleet.drain()
    fd = counters.delta(fbefore)
    del fleet, model

    leg = {"sessions": n_sessions, "passes": 2,
           "max_new_tokens": max_new,
           "block_size": bs,
           "working_set_blocks": demand,
           "kv_blocks_base": nb_base,
           "kv_blocks_2x": nb_2x,
           "kv_blocks_4x": nb_4x,
           "host_kv_blocks": demand,
           "decode_tokens_per_sec_base": round(tps_base, 2),
           "decode_tokens_per_sec_2x": round(tps_2x, 2),
           "decode_tokens_per_sec_4x": round(tps_4x, 2),
           "retention_2x": round(tps_2x / max(tps_base, 1e-9), 4),
           "retention_4x": round(tps_4x / max(tps_base, 1e-9), 4),
           "spilled_blocks": d2.get("serving.kv.tier.spilled_blocks", 0),
           "restored_blocks": d2.get("serving.kv.tier.restored_blocks", 0),
           "readopted": d2.get("serving.kv.tier.readopted", 0),
           "host_buf_reuse": d2.get("serving.kv.host_buf_reuse", 0),
           "spilled_blocks_4x": d4.get("serving.kv.tier.spilled_blocks",
                                       0),
           "sheds": sheds_base + sheds_2x + sheds_4x,
           "steady_retraces_2x": d2.get("serving.retraces", 0),
           "outputs_match_generate": True,
           "fleet": {
               "prefix_routed": fd.get("serving.fleet.prefix_routed", 0),
               "tier_spilled": fd.get("serving.kv.tier.spilled_blocks",
                                      0),
               "tier_restored": fd.get("serving.kv.tier.restored_blocks",
                                       0),
               "sheds": fd.get("serving.fleet.shed", 0),
               "lost": fd.get("serving.fleet.lost", 0)}}
    if leg["sheds"] != 0:
        raise AssertionError(
            f"tiered leg shed/errored requests under oversubscription: "
            f"{leg}")
    if leg["spilled_blocks"] < 1 or leg["restored_blocks"] < 1:
        raise AssertionError(
            f"tiered leg moved no blocks through the host tier at 2x "
            f"oversubscription — the leg is not exercising tiering: "
            f"{leg}")
    if leg["retention_2x"] < min_retention:
        raise AssertionError(
            f"tiered leg decode retention {leg['retention_2x']:.3f}x at "
            f"2x oversubscription below the {min_retention:.2f}x floor: "
            f"{leg}")
    if (leg["fleet"]["lost"] != 0 or leg["fleet"]["sheds"] != 0
            or leg["fleet"]["prefix_routed"] < 1):
        raise AssertionError(
            f"tiered leg fleet pass broke the prefix-economy "
            f"invariants: {leg}")
    return leg


def _parse_mesh_degrees(spec):
    """Parse a ``PTPU_MESH`` string like ``dp2``, ``dp4`` or ``dp2mp2``
    into an ordered ``{axis_name: degree}`` dict."""
    import re

    degrees = {}
    for name, num in re.findall(r"([a-z]+)(\d+)", (spec or "").lower()):
        degrees[name] = int(num)
    return degrees or {"dp": 2}


def _run_servemp_leg(cfg, mp, n_requests=8, max_new=24, max_slots=8,
                     min_bucket=8, block_size=16, prefill_chunk=128,
                     seed=0, max_hbm_frac=0.6, min_tps_frac=0.9):
    """Tensor-parallel paged serving duel: an mp-way mesh engine
    (``LLMEngine(mesh=...)`` — KV pool head-sharded, Megatron-sharded
    weights, replicated operand block tables, in-graph collectives only)
    vs the unsharded engine at EQUAL admitted capacity (same slots, same
    block pool).  Gates: token identity, zero steady retraces on the
    mesh path, per-chip KV-pool + weight HBM bytes <= ``max_hbm_frac``
    of the single-chip figure, and decode tok/s within
    ``1 - min_tps_frac`` of the unsharded baseline (honest on real
    chips; on the forced-host CPU fallback the "chips" share cores, so
    the throughput gate is informational there).  Returns the leg
    dict."""
    import jax
    from jax.sharding import Mesh

    import paddle_tpu as paddle
    from paddle_tpu.models import GPTForCausalLM
    from paddle_tpu.profiler import counters
    from paddle_tpu.serving import LLMEngine

    paddle.seed(seed)
    model = GPTForCausalLM(cfg)
    model.eval()
    rng = np.random.RandomState(seed)
    S = cfg.max_seq_len
    # decode-heavy mix: short prompts, long generations — the regime
    # tensor parallelism serves (per-token weight sweep dominates)
    lens = [int(rng.randint(max(2, S // 32), max(3, S // 8)))
            for _ in range(n_requests)]
    prompts = [rng.randint(0, cfg.vocab_size, size=n).tolist()
               for n in lens]

    def build(mesh=None):
        return LLMEngine(model, max_slots=max_slots, max_seq_len=S,
                         min_bucket=min_bucket, kv_layout="paged",
                         block_size=block_size,
                         prefill_chunk=prefill_chunk, mesh=mesh)

    def serve(eng):
        hs = [eng.add_request(p, max_new_tokens=max_new) for p in prompts]
        while not all(h.is_finished for h in hs):
            eng.step()
        return [list(map(int, h.tokens)) for h in hs]

    def timed(eng, rounds=3):
        best, toks = 0.0, None
        for _ in range(rounds):
            t0 = time.perf_counter()
            toks = serve(eng)
            tps = (n_requests * max_new
                   / max(time.perf_counter() - t0, 1e-9))
            best = max(best, tps)
        return toks, best

    base = build()
    base_tokens = serve(base)    # warm: full prefills
    serve(base)                  # warm: prefix-cached re-prefills
    _, base_tps = timed(base)
    base_stats = base.stats()
    base_bytes = (base_stats["kv_pool_bytes_per_chip"]
                  + base_stats["weight_bytes_per_chip"])

    mesh = Mesh(np.array(jax.devices()[:mp]).reshape(mp), ("mp",))
    sh = build(mesh)
    sh_tokens = serve(sh)        # warm: full prefills ([mp] programs)
    serve(sh)                    # warm: prefix-cached re-prefills
    before = counters.snapshot()
    sh_tokens2, sh_tps = timed(sh)
    delta = counters.delta(before)
    sh_stats = sh.stats()
    sh_bytes = (sh_stats["kv_pool_bytes_per_chip"]
                + sh_stats["weight_bytes_per_chip"])

    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    leg = {"mesh": f"mp{mp}",
           "cpu_fallback": not on_tpu,
           "requests": n_requests,
           "max_new_tokens": max_new,
           "decode_tokens_per_sec": round(sh_tps, 2),
           "decode_tokens_per_sec_per_chip": round(sh_tps / mp, 2),
           "unsharded_tokens_per_sec": round(base_tps, 2),
           "tps_frac_vs_unsharded": round(sh_tps / max(base_tps, 1e-9), 4),
           "kv_pool_bytes_per_chip": sh_stats["kv_pool_bytes_per_chip"],
           "weight_bytes_per_chip": sh_stats["weight_bytes_per_chip"],
           "unsharded_kv_pool_bytes": base_stats["kv_pool_bytes_per_chip"],
           "unsharded_weight_bytes": base_stats["weight_bytes_per_chip"],
           "per_chip_hbm_frac": round(sh_bytes / max(base_bytes, 1), 4),
           "outputs_match_unsharded": (sh_tokens == base_tokens
                                       and sh_tokens2 == base_tokens),
           "steady_retraces": delta.get("serving.retraces", 0),
           "spec_degraded": counters.get("serving.mesh.spec_degraded"),
           "kv_shard_shape": list(sh.arena.shard_shape("pool_k"))}
    if not leg["outputs_match_unsharded"]:
        raise AssertionError(
            f"servemp leg: mp{mp} engine diverged from unsharded: {leg}")
    if leg["steady_retraces"]:
        raise AssertionError(
            f"servemp leg: {leg['steady_retraces']} steady retraces on "
            f"the mesh path: {leg}")
    if leg["per_chip_hbm_frac"] > max_hbm_frac:
        raise AssertionError(
            f"servemp leg: per-chip KV+weight bytes "
            f"{leg['per_chip_hbm_frac']:.3f}x of unsharded exceed the "
            f"{max_hbm_frac:.2f}x ceiling: {leg}")
    if leg["tps_frac_vs_unsharded"] < min_tps_frac:
        raise AssertionError(
            f"servemp leg: mesh decode tok/s "
            f"{leg['tps_frac_vs_unsharded']:.3f}x of unsharded below the "
            f"{min_tps_frac:.2f}x floor: {leg}")
    del base, sh, model
    return leg


def _run_mesh_leg(cfg, batch_per_chip, seq, iters, rounds, degrees,
                  fused_steps=1, peak=197e12, min_scaling=None):
    """Multi-chip SPMD leg: the same fused training loop run mesh-native
    (``CompiledTrainStep(mesh=...)`` — sharded donated carry, batch staged
    with data-parallel ``NamedSharding``), weak-scaled (constant per-chip
    batch) against a mesh(1) run of the *identical* code path.  Gates the
    steady-state counter contract on the mesh path (zero retraces /
    rehydrates / host binds, dispatches == steps/K) and, when
    ``min_scaling`` is set (real chips only), the dp scaling-efficiency
    floor.  Returns the leg dict."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import paddle_tpu as paddle
    from paddle_tpu.core import flags as _flags
    from paddle_tpu.io import Window
    from paddle_tpu.jit import CompiledTrainStep
    from paddle_tpu.models import GPTForCausalLM, GPTPretrainingCriterion
    from paddle_tpu.profiler import counters
    from paddle_tpu.profiler import metrics as _pm

    k = max(1, int(fused_steps))

    def one(deg):
        # Always carry an "mp" axis (size 1 if unrequested) so any
        # model-declared tensor-parallel placements resolve on the mesh.
        axes = dict(deg)
        if "mp" not in axes:
            axes["mp"] = 1
        ndev = int(np.prod(list(axes.values())))
        if jax.device_count() < ndev:
            raise SystemExit(
                f"mesh leg needs {ndev} devices for {deg}, have "
                f"{jax.device_count()}")
        mesh = Mesh(
            np.array(jax.devices()[:ndev]).reshape(
                tuple(axes.values())),
            tuple(axes.keys()))
        dp = int(np.prod([v for a, v in axes.items()
                          if a in ("dp", "sharding")]))
        batch = batch_per_chip * dp

        paddle.seed(1234)
        model = GPTForCausalLM(cfg)
        crit = GPTPretrainingCriterion()
        opt = paddle.optimizer.AdamW(1e-4, parameters=model.parameters())
        ids = paddle.randint(0, cfg.vocab_size, [batch, seq])
        labels = paddle.randint(0, cfg.vocab_size, [batch, seq])

        def loss_fn(m, x, l):
            return crit(m(x), l)

        step = CompiledTrainStep(model, loss_fn, opt, fused_steps=k,
                                 mesh=mesh)
        # Stage the batch with its data-parallel sharding up front — the
        # steady loop then re-feeds committed sharded arrays, exercising
        # the same placement the prefetchers produce.
        if step._batch_spec is not None:
            sh = NamedSharding(mesh, step._batch_spec)
            wsh = NamedSharding(mesh, P(None, *step._batch_spec))
            ids = paddle.Tensor(jax.device_put(ids._data, sh))
            labels = paddle.Tensor(jax.device_put(labels._data, sh))
        if k > 1:
            stacked = [np.stack([np.asarray(t.numpy())] * k)
                       for t in (ids, labels)]
            if step._batch_spec is not None:
                stacked = [jax.device_put(s, wsh) for s in stacked]
            win = Window(tuple(paddle.to_tensor(s) for s in stacked), k)
            dispatch = lambda: step(win)
        else:
            dispatch = lambda: step(ids, labels)

        t0 = time.perf_counter()
        dispatch()
        dispatch().numpy()
        compile_s = time.perf_counter() - t0
        dispatch().numpy()  # first fully cached dispatch

        n_windows = max(1, iters // k)
        before = counters.snapshot()
        rates = []
        for _ in range(rounds):
            t0 = time.perf_counter()
            for _ in range(n_windows):
                loss = dispatch()
            loss.numpy()  # sync
            dt = time.perf_counter() - t0
            rates.append(batch * seq * k * n_windows / dt)
        delta = counters.delta(before)
        tps = float(np.median(rates))
        steady = {"retraces": delta.get("jit.traces", 0),
                  "rehydrates": delta.get("jit.hydrates", 0),
                  "host_binds": (delta.get("jit.host.bind_layer_state", 0)
                                 + delta.get(
                                     "jit.host.bind_optimizer_state", 0)),
                  "dispatches": delta.get("jit.host.dispatches", 0),
                  "windows": rounds * n_windows}
        if (steady["retraces"] or steady["rehydrates"]
                or steady["host_binds"]
                or steady["dispatches"] != steady["windows"]):
            raise AssertionError(
                f"mesh leg broke the steady-state counter contract on "
                f"mesh {deg}: {steady}")
        n_params = sum(int(np.prod(p.shape))
                       for p in model.parameters())
        del step, model, opt  # free HBM before the next mesh
        return tps, ndev, n_params, round(compile_s, 4), steady

    base_tps, _, _, base_compile_s, _ = one(
        {a: 1 for a in degrees})
    # device telemetry ON for the mesh pass: per-program HBM bytes (XLA
    # memory analysis at the compile site) land in program_stats; the AOT
    # lower happens at warmup, so the steady-state gate is unaffected
    _flags.set_flags({"FLAGS_device_telemetry": True})
    try:
        tps, ndev, n_params, compile_s, steady = one(degrees)
    finally:
        _flags.set_flags({"FLAGS_device_telemetry": False})
    hbm = {name: {f: st.get(f) for f in
                  ("arg_bytes", "out_bytes", "temp_bytes", "compile_s")}
           for name, st in _pm.program_stats().items()
           if name.startswith("jit.")}
    tps_chip = tps / ndev
    eff = tps_chip / base_tps
    leg = {"mesh": dict(degrees),
           "n_chips": ndev,
           "fused_steps": k,
           "batch_per_chip": batch_per_chip,
           "tokens_per_sec": round(tps, 2),
           "tokens_per_sec_per_chip": round(tps_chip, 2),
           "single_chip_tokens_per_sec": round(base_tps, 2),
           "scaling_efficiency": round(eff, 4),
           "mfu": round(tps_chip * 6 * n_params / peak, 4),
           "compile_s": compile_s,
           "single_chip_compile_s": base_compile_s,
           "steady": steady,
           "hbm": hbm}
    if min_scaling is not None and eff < min_scaling:
        raise AssertionError(
            f"mesh leg scaling efficiency {eff:.3f} below the "
            f"{min_scaling:.2f} floor: {leg}")
    return leg


def main():
    # the mesh leg (and its CPU fallback) needs >1 device; forcing host
    # devices is a no-op on real TPU platforms and must happen before the
    # first jax import.
    if ("--xla_force_host_platform_device_count"
            not in os.environ.get("XLA_FLAGS", "")):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8").strip()
    if os.environ.get("PTPU_BENCH_SMOKE") == "1":
        # perf-contract smoke leg: asserts steady-state steps do zero
        # host-side hydrate/bind work (see scripts/bench_smoke.py)
        import sys
        sys.path.insert(0, os.path.join(os.path.dirname(
            os.path.abspath(__file__)), "scripts"))
        import bench_smoke
        bench_smoke.run()
        return

    import jax

    from paddle_tpu.models import GPTConfig

    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    peak = 197e12  # v5e bf16 peak (394e12 is int8)

    fused_k = int(os.environ.get("PTPU_FUSED_STEPS", "4"))

    if not on_tpu and os.environ.get("PTPU_BENCH") == "servemp":
        # tensor-parallel serving twin, runnable in isolation off-TPU:
        # same gates as the flagship (token identity, zero steady
        # retraces, per-chip KV+weight HBM <= 0.6x single-chip, decode
        # tok/s >= 0.9x unsharded) at the flagship's 1536 width (depth
        # truncated for CPU wall-clock) — width is what the tok/s gate
        # exercises: per-layer matmul work grows quadratically with it
        # while the all-reduce bytes grow linearly, so the mp overhead
        # amortizes the same way it does on real chips
        mp = _parse_mesh_degrees(os.environ.get("PTPU_MESH", "mp2")
                                 ).get("mp", 2)
        vcfg = GPTConfig(vocab_size=50304, hidden_size=1536,
                         num_layers=6, num_heads=16, max_seq_len=256,
                         dtype="float32", use_flash_attention=False)
        leg = _run_servemp_leg(vcfg, mp, n_requests=6, max_new=24,
                               max_slots=6, block_size=16,
                               prefill_chunk=64)
        print(json.dumps({
            "metric": "gpt760m_servemp_decode_tokens_per_sec_per_chip",
            "value": leg["decode_tokens_per_sec_per_chip"],
            "unit": "tokens/s/chip",
            "vs_baseline": leg["per_chip_hbm_frac"],  # KV+W vs 1 chip
            "tps_frac_vs_unsharded": leg["tps_frac_vs_unsharded"],
            "legs": {"gpt760m_servemp": leg},
        }))
        return

    if not on_tpu:  # CPU fallback so the bench always produces a line
        cfg = GPTConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                        num_heads=4, max_seq_len=128,
                        use_flash_attention=False)
        tps, spread, _, phases, msum, gput = _run_leg(cfg, 2, 128, 4, 1)
        out = {"metric": "gpt_tiny_cpu_tokens_per_sec",
               "value": round(tps, 2), "unit": "tokens/s",
               "vs_baseline": 0.0,
               "spread_frac": round(spread, 4),
               "phases": phases,
               "metrics": msum,
               "goodput": gput}
        if fused_k > 1:
            ftps, _, _, fphases, fmsum, fgput = _run_leg(
                cfg, 2, 128, 4, 1, fused_steps=fused_k)
            out["fused"] = {"fused_steps": fused_k,
                            "tokens_per_sec": round(ftps, 2),
                            "fused_speedup": round(ftps / tps, 4),
                            "phases": fphases,
                            "metrics": fmsum,
                            "goodput": fgput}
        # tiny serving leg: correctness gate (token identity) always; the
        # speedup number is informational on CPU
        out["serve"] = _run_serve_leg(cfg, n_requests=64, max_new=8,
                                      max_slots=4, min_bucket=4)
        # tiny checkpoint leg: async-save overlap + one-sync-per-save
        # budget (overhead number is informational on CPU)
        out["ckpt"] = _run_ckpt_leg(cfg, 2, 128, 4,
                                    fused_steps=max(1, fused_k))
        # tiny paged-KV leg: capacity / prefix-cache / identity gates
        # always; throughput numbers informational on CPU
        out["paged"] = _run_paged_leg(cfg, n_requests=24, max_new=8,
                                      max_slots=4, min_bucket=4,
                                      block_size=4, prefill_chunk=16,
                                      n_verify=4)
        # tiny quantized-KV leg: >=2x admitted capacity at the same KV
        # byte budget (fp32 arena -> ~4x blocks on CPU); throughput
        # informational
        out["paged_q"] = _run_paged_q_leg(cfg, n_requests=48, max_new=8,
                                          max_slots=2, min_bucket=4,
                                          block_size=4, prefill_chunk=16,
                                          n_verify=4)
        # tiny KV-tiering leg: identity / zero-shed / spill+restore
        # traffic gates at 2x-4x oversubscription always; the decode
        # retention number is informational-grade on CPU but still
        # gated at the same 0.5x floor (host restore is a memcpy)
        out["tiered"] = _run_tiered_leg(cfg, n_sessions=8, max_new=8,
                                        max_slots=4, min_bucket=4,
                                        block_size=4, prefill_chunk=16,
                                        n_verify=4)
        # tiny speculative leg: greedy identity + counter-identity gates
        # and the >=1.3x net decode speedup of the aligned draft/target
        # pair (the target's zeroed-weight sweep is bandwidth-bound on
        # CPU too, so the verify amortization is measurable off-TPU)
        out["spec"] = _run_spec_leg(n_requests=8, max_new=16,
                                    max_slots=4, min_bucket=4,
                                    block_size=8, prefill_chunk=16,
                                    hidden=512, layers=12, vocab=512,
                                    seq_len=128)
        # tiny fleet leg: durability gates (zero lost, respawn == kills,
        # churn output identical) always; throughput informational on CPU
        out["fleet"] = _run_fleet_leg(cfg, replicas=2, n_requests=4,
                                      max_new=8, max_slots=2,
                                      min_bucket=4)
        # tiny disaggregated leg: prefill/decode split vs unified at
        # equal replica count — p95 ITL win (>=1.3x), migration block
        # accounting, token identity and churn durability gates always
        out["disagg"] = _run_disagg_leg(cfg, n_long=4, n_short=12,
                                        max_new=32, min_bucket=4,
                                        block_size=8, prefill_chunk=16)
        # tiny multi-tenant adapter leg: identity / zero-lost /
        # load-drop-recovery / paging gates always; throughput and
        # noisy-neighbor skew informational on CPU
        out["multitenant"] = _run_multitenant_leg(
            cfg, replicas=2, tenants=6, adapter_slots=4, rank=4,
            n_requests=12, max_new=16, max_slots=4, min_bucket=4,
            block_size=4, prefill_chunk=16)
        # tiny mesh leg: steady-state counter gates on the multi-chip
        # SPMD path always; scaling efficiency is informational on
        # forced-host CPU "devices" (they share the same cores)
        if jax.device_count() >= 2:
            out["mesh"] = _run_mesh_leg(
                cfg, 2, 128, 4, 1,
                _parse_mesh_degrees(os.environ.get("PTPU_MESH", "dp2")),
                fused_steps=max(1, fused_k), peak=peak)
        print(json.dumps(out))
        return

    which = os.environ.get("PTPU_BENCH", "all")
    if which not in ("all", "760m", "125m", "serve", "paged", "paged_q",
                     "tiered", "spec", "ckpt", "fleet", "disagg", "mesh",
                     "mesh760m", "servemp", "multitenant"):
        raise SystemExit(
            f"PTPU_BENCH={which!r}: expected "
            f"all|760m|125m|serve|paged|paged_q|tiered|spec|ckpt|fleet|"
            f"disagg|mesh|mesh760m|servemp|multitenant")
    mesh_degrees = _parse_mesh_degrees(os.environ.get("PTPU_MESH", "dp2"))
    mesh_ndev = int(np.prod(list(mesh_degrees.values())))
    legs = {}
    if which in ("all", "760m"):
        cfg = GPTConfig.gpt3_760m(vocab_size=50304, max_seq_len=1024,
                                  dtype="bfloat16",
                                  use_flash_attention=True,
                                  recompute="selective_lean")
        # rounds=4: the first post-compile round can run ~3% cold (seen in
        # r5 combined runs); the median over 4 shakes it off
        tps, spread, n, phases, msum, gput = _run_leg(cfg, 8, 1024, 10, 4)
        legs["gpt760m"] = {"tokens_per_sec": round(tps, 2),
                           "mfu": round(tps * 6 * n / peak, 4),
                           "spread_frac": round(spread, 4),
                           "phases": phases,
                           "metrics": msum,
                           "goodput": gput}
    if which in ("all", "125m"):
        cfg = GPTConfig.gpt3_125m(vocab_size=50304, max_seq_len=1024,
                                  dtype="bfloat16",
                                  use_flash_attention=True,
                                  recompute="selective")
        tps, spread, n, phases, msum, gput = _run_leg(cfg, 16, 1024, 15, 3)
        legs["gpt125m"] = {"tokens_per_sec": round(tps, 2),
                           "mfu": round(tps * 6 * n / peak, 4),
                           "spread_frac": round(spread, 4),
                           "phases": phases,
                           "metrics": msum,
                           "goodput": gput}
        if fused_k > 1:
            # fused-dispatch leg: same model/config, K steps per XLA
            # launch — isolates the per-step python dispatch overhead
            # that the 125m leg is most exposed to
            ftps, fspread, n, fphases, fmsum, fgput = _run_leg(
                cfg, 16, 1024, 16, 3, fused_steps=fused_k)
            legs["gpt125m_fused"] = {
                "fused_steps": fused_k,
                "tokens_per_sec": round(ftps, 2),
                "mfu": round(ftps * 6 * n / peak, 4),
                "fused_speedup": round(ftps / tps, 4),
                "spread_frac": round(fspread, 4),
                "phases": fphases,
                "metrics": fmsum,
                "goodput": fgput}
    if which in ("all", "ckpt"):
        # checkpointed-training leg: steady fused windows with async saves
        # overlapping the next window — reports ckpt_overhead_frac and
        # gates the one-sync-per-save counter budget
        ccfg = GPTConfig.gpt3_125m(vocab_size=50304, max_seq_len=1024,
                                   dtype="bfloat16",
                                   use_flash_attention=True,
                                   recompute="selective")
        legs["gpt125m_ckpt"] = _run_ckpt_leg(ccfg, 16, 1024, 16,
                                             fused_steps=max(1, fused_k))
    if which in ("all", "serve"):
        # serving leg: continuous batching over 64 staggered mixed-length
        # requests with TTFT/ITL/queue-wait percentiles (acceptance:
        # serve_speedup > 1 on TPU, verified prefix token-identical to
        # sequential generate always)
        scfg = GPTConfig.gpt3_125m(vocab_size=50304, max_seq_len=1024,
                                   dtype="bfloat16",
                                   use_flash_attention=False,
                                   recompute=None)
        legs["gpt125m_serve"] = _run_serve_leg(scfg, n_requests=64,
                                               max_new=64, max_slots=8)
    if which in ("all", "paged"):
        # paged-KV leg: >=2x admitted concurrency at the slot arena's KV
        # HBM on mixed lengths, plus shared-system-prompt TTFT tails and
        # the prefix-cache hit / reduced-prefill gates
        pcfg = GPTConfig.gpt3_125m(vocab_size=50304, max_seq_len=1024,
                                   dtype="bfloat16",
                                   use_flash_attention=False,
                                   recompute=None)
        legs["gpt125m_paged"] = _run_paged_leg(pcfg, n_requests=64,
                                               max_new=64, max_slots=8,
                                               block_size=16,
                                               prefill_chunk=256)
    if which in ("all", "paged_q"):
        # quantized-KV leg: int8 arena vs bf16 paged at the same KV HBM
        # byte budget — >=2x admitted concurrency, decode tok/s no worse
        qcfg = GPTConfig.gpt3_125m(vocab_size=50304, max_seq_len=1024,
                                   dtype="bfloat16",
                                   use_flash_attention=False,
                                   recompute=None)
        legs["gpt125m_paged_q"] = _run_paged_q_leg(qcfg, n_requests=64,
                                                   max_new=64, max_slots=4,
                                                   block_size=16,
                                                   prefill_chunk=256)
    if which in ("all", "tiered"):
        # KV-tiering leg: device pool cut to 1/2 and 1/4 of the working
        # set with a pinned host tier covering the difference — gates
        # token identity, zero sheds, live spill/restore traffic and
        # >=0.5x decode retention at 2x oversubscription, plus the
        # fleet router's host-aware prefix-affinity wins
        tcfg = GPTConfig.gpt3_125m(vocab_size=50304, max_seq_len=1024,
                                   dtype="bfloat16",
                                   use_flash_attention=False,
                                   recompute=None)
        legs["gpt125m_tiered"] = _run_tiered_leg(tcfg, n_sessions=24,
                                                 max_new=64, max_slots=8,
                                                 block_size=16,
                                                 prefill_chunk=256)
    if which in ("all", "spec"):
        # speculative-decoding leg: aligned draft/target pair (shared
        # embeddings, zeroed blocks -> acceptance ~1.0) at gpt125m width
        # and depth — acceptance rate, net decode tok/s vs the non-spec
        # paged baseline (>= 1.3x), TTFT/ITL tails, zero steady retraces
        legs["gpt125m_spec"] = _run_spec_leg(n_requests=32, max_new=64,
                                             max_slots=8, hidden=768,
                                             layers=12, vocab=50304,
                                             seq_len=1024, block_size=16,
                                             prefill_chunk=256)
    if which in ("all", "fleet"):
        # elastic-fleet leg: multi-replica throughput with and without
        # one replica killed mid-decode (acceptance: zero lost requests,
        # churn output token-identical to the clean run)
        fcfg = GPTConfig.gpt3_125m(vocab_size=50304, max_seq_len=1024,
                                   dtype="bfloat16",
                                   use_flash_attention=False,
                                   recompute=None)
        legs["gpt125m_fleet"] = _run_fleet_leg(fcfg, replicas=2,
                                               n_requests=8, max_new=64,
                                               max_slots=4)
    if which in ("all", "multitenant"):
        # multi-tenant adapter leg: 6 LoRA tenants through a 2-replica
        # fleet whose per-replica arena holds 4 — cold tenants page in,
        # LRU evicts idle (acceptance: fair pass token-identical across
        # repeats with zero steady retraces, zero lost, the injected
        # adapter_load_drop recovering to a finished request, and
        # loads/evictions both moving — paging genuinely exercised)
        mtcfg = GPTConfig.gpt3_125m(vocab_size=50304, max_seq_len=1024,
                                    dtype="bfloat16",
                                    use_flash_attention=False,
                                    recompute=None)
        legs["gpt125m_multitenant"] = _run_multitenant_leg(
            mtcfg, replicas=2, tenants=6, adapter_slots=4, rank=8,
            n_requests=12, max_new=64, max_slots=4, block_size=16,
            prefill_chunk=256)
    if which in ("all", "disagg"):
        # disaggregated prefill/decode leg: 1+1 split vs 2-replica
        # unified on mixed long/short traffic (acceptance: >=1.3x p95
        # ITL win at equal replica count, zero lost under migration
        # chaos, token identity to the unified fleet)
        dcfg = GPTConfig.gpt3_125m(vocab_size=50304, max_seq_len=1024,
                                   dtype="bfloat16",
                                   use_flash_attention=False,
                                   recompute=None)
        legs["gpt125m_disagg"] = _run_disagg_leg(dcfg, n_long=6,
                                                 n_short=18, max_new=64,
                                                 block_size=16,
                                                 prefill_chunk=256)
    if which == "mesh" or (which == "all"
                           and jax.device_count() >= mesh_ndev):
        # multi-chip SPMD leg: weak-scaled fused training on the
        # PTPU_MESH mesh vs a mesh(1) run of the same code path
        # (acceptance: zero steady retraces, dispatches == steps/K,
        # >=70% dp scaling efficiency)
        mcfg = GPTConfig.gpt3_125m(vocab_size=50304, max_seq_len=1024,
                                   dtype="bfloat16",
                                   use_flash_attention=True,
                                   recompute="selective")
        legs["gpt125m_mesh"] = _run_mesh_leg(mcfg, 16, 1024, 16, 3,
                                             mesh_degrees,
                                             fused_steps=max(1, fused_k),
                                             peak=peak, min_scaling=0.70)
    if which == "servemp":
        # tensor-parallel serving leg: mp-way mesh paged engine vs the
        # unsharded engine at EQUAL admitted capacity (acceptance: token
        # identity, zero steady retraces, per-chip KV+weight HBM <= 0.6x
        # single-chip, decode tok/s >= 0.9x unsharded). Runs the 760m
        # flagship — only reachable on TPU; off-TPU the CPU-fallback
        # twin earlier in main() handles PTPU_BENCH=servemp.
        mp = _parse_mesh_degrees(os.environ.get("PTPU_MESH", "mp2")
                                 ).get("mp", 2)
        vcfg = GPTConfig.gpt3_760m(vocab_size=50304, max_seq_len=1024,
                                   dtype="bfloat16",
                                   use_flash_attention=False,
                                   recompute=None)
        legs["gpt760m_servemp"] = _run_servemp_leg(
            vcfg, mp, n_requests=16, max_new=64, max_slots=8,
            block_size=16, prefill_chunk=256)
    if which == "mesh760m":
        mcfg = GPTConfig.gpt3_760m(vocab_size=50304, max_seq_len=1024,
                                   dtype="bfloat16",
                                   use_flash_attention=True,
                                   recompute="selective_lean")
        legs["gpt760m_mesh"] = _run_mesh_leg(mcfg, 8, 1024, 8, 3,
                                             mesh_degrees,
                                             fused_steps=max(1, fused_k),
                                             peak=peak, min_scaling=0.70)

    if set(legs) in ({"gpt125m_mesh"}, {"gpt760m_mesh"}):
        # mesh-only run: per-chip throughput line, MFU as vs_baseline
        name, = legs
        leg = legs[name]
        print(json.dumps({
            "metric": f"{name}_train_tokens_per_sec_per_chip",
            "value": leg["tokens_per_sec_per_chip"],
            "unit": "tokens/s/chip",
            "vs_baseline": leg["mfu"],  # true MFU fraction (bf16 peak)
            "scaling_efficiency": leg["scaling_efficiency"],
            "legs": legs,
        }))
        return
    if set(legs) == {"gpt760m_servemp"}:  # servemp-only: per-chip line
        leg = legs["gpt760m_servemp"]
        print(json.dumps({
            "metric": "gpt760m_servemp_decode_tokens_per_sec_per_chip",
            "value": leg["decode_tokens_per_sec_per_chip"],
            "unit": "tokens/s/chip",
            "vs_baseline": leg["per_chip_hbm_frac"],  # KV+W vs 1 chip
            "tps_frac_vs_unsharded": leg["tps_frac_vs_unsharded"],
            "legs": legs,
        }))
        return
    if set(legs) == {"gpt125m_fleet"}:  # fleet-only run: durability line
        leg = legs["gpt125m_fleet"]
        print(json.dumps({
            "metric": "gpt125m_fleet_decode_tokens_per_sec",
            "value": leg["decode_tokens_per_sec"],
            "unit": "tokens/s",
            "vs_baseline": leg["churn_retention"],  # vs one replica killed
            "legs": legs,
        }))
        return
    if set(legs) == {"gpt125m_multitenant"}:  # adapters-only: tenant line
        leg = legs["gpt125m_multitenant"]
        print(json.dumps({
            "metric": "gpt125m_multitenant_decode_tokens_per_sec",
            "value": leg["decode_tokens_per_sec"],
            "unit": "tokens/s (6 tenants + base, one decode program)",
            "vs_baseline": leg["tenants_per_slot"],  # variants per slot
            "noisy_itl_p95_skew": leg["noisy_itl_p95_skew"],
            "legs": legs,
        }))
        return
    if set(legs) == {"gpt125m_disagg"}:  # disagg-only: ITL-win line
        leg = legs["gpt125m_disagg"]
        print(json.dumps({
            "metric": "gpt125m_disagg_itl_p95_speedup",
            "value": leg["itl_p95_speedup"],
            "unit": "x unified p95 ITL at equal replica count",
            "vs_baseline": leg["disagg_itl"]["p95_ms"],
            "legs": legs,
        }))
        return
    if set(legs) == {"gpt125m_spec"}:  # spec-only run: speedup line
        leg = legs["gpt125m_spec"]
        print(json.dumps({
            "metric": "gpt125m_spec_decode_tokens_per_sec",
            "value": leg["decode_tokens_per_sec_spec"],
            "unit": "tokens/s",
            "vs_baseline": leg["spec_speedup"],  # vs non-spec paged
            "acceptance_rate": leg["acceptance_rate"],
            "legs": legs,
        }))
        return
    if set(legs) == {"gpt125m_tiered"}:  # tiered-only: retention line
        leg = legs["gpt125m_tiered"]
        print(json.dumps({
            "metric": "gpt125m_tiered_decode_tokens_per_sec_2x",
            "value": leg["decode_tokens_per_sec_2x"],
            "unit": "tokens/s at 2x oversubscribed KV",
            "vs_baseline": leg["retention_2x"],  # vs ample-pool paged
            "retention_4x": leg["retention_4x"],
            "legs": legs,
        }))
        return
    if set(legs) == {"gpt125m_paged_q"}:  # paged_q-only: quant capacity
        leg = legs["gpt125m_paged_q"]
        print(json.dumps({
            "metric": "gpt125m_paged_q_admitted_capacity_ratio",
            "value": leg["capacity_ratio"],
            "unit": "x admitted vs bf16 paged at fixed KV HBM",
            "vs_baseline": leg["decode_parity"],  # quant vs raw tok/s
            "legs": legs,
        }))
        return
    if set(legs) == {"gpt125m_paged"}:  # paged-only run: capacity line
        leg = legs["gpt125m_paged"]
        print(json.dumps({
            "metric": "gpt125m_paged_decode_tokens_per_sec",
            "value": leg["decode_tokens_per_sec_paged"],
            "unit": "tokens/s",
            "vs_baseline": leg["capacity_ratio"],  # peak admits vs slots
            "legs": legs,
        }))
        return
    if set(legs) == {"gpt125m_ckpt"}:  # ckpt-only run: overhead line
        leg = legs["gpt125m_ckpt"]
        print(json.dumps({
            "metric": "gpt125m_ckpt_tokens_per_sec",
            "value": leg["tokens_per_sec"],
            "unit": "tokens/s",
            "vs_baseline": leg["ckpt_overhead_frac"],  # vs bare loop
            "legs": legs,
        }))
        return
    flag = ("gpt760m" if "gpt760m" in legs
            else "gpt125m" if "gpt125m" in legs else "gpt125m_serve")
    if flag == "gpt125m_serve":  # serve-only run: decode throughput line
        leg = legs[flag]
        print(json.dumps({
            "metric": "gpt125m_serve_decode_tokens_per_sec",
            "value": leg["decode_tokens_per_sec"],
            "unit": "tokens/s",
            "vs_baseline": leg["serve_speedup"],  # vs sequential generate
            "legs": legs,
        }))
        return
    print(json.dumps({
        "metric": f"{flag}_train_tokens_per_sec_per_chip",
        "value": legs[flag]["tokens_per_sec"],
        "unit": "tokens/s",
        "vs_baseline": legs[flag]["mfu"],  # true MFU fraction (bf16 peak)
        "spread_frac": legs[flag]["spread_frac"],
        "legs": legs,
    }))


if __name__ == "__main__":
    main()
