"""Benchmark: GPT causal-LM training throughput on one TPU chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline anchor (BASELINE.md): the reference publishes no in-repo numbers;
the driver-defined north star is GPT MFU.  We report tokens/sec/chip for a
GPT-125M-class model with the compiled train step; ``vs_baseline`` is true
model-FLOPs utilisation from 6*N FLOPs/token against the v5e **bf16** peak
of 197 TFLOP/s (394 TFLOP/s is the int8 number).

Config notes (perf round 4): batch 16 x 1024 with Megatron-style selective
recompute (saves qkv/attn_out/ffn_up, replays norms+gelu+flash in bwd) beats
batch 8 without remat; the CE loss is the fused lse-picked form.
"""

import json
import time

import numpy as np


def main():
    import jax

    import paddle_tpu as paddle
    from paddle_tpu.jit import CompiledTrainStep
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainingCriterion)

    on_tpu = jax.devices()[0].platform in ("tpu", "axon")
    # GPT-125M-class, bf16 on TPU
    if on_tpu:
        cfg = GPTConfig.gpt3_125m(vocab_size=50304, max_seq_len=1024,
                                  dtype="bfloat16",
                                  use_flash_attention=True,
                                  recompute="selective")
        batch, seq = 16, 1024
    else:  # CPU fallback so the bench always produces a line
        cfg = GPTConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                        num_heads=4, max_seq_len=128,
                        use_flash_attention=False)
        batch, seq = 2, 128

    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion()
    opt = paddle.optimizer.AdamW(1e-4, parameters=model.parameters())
    ids = paddle.randint(0, cfg.vocab_size, [batch, seq])
    labels = paddle.randint(0, cfg.vocab_size, [batch, seq])

    def loss_fn(m, x, l):
        return crit(m(x), l)

    step = CompiledTrainStep(model, loss_fn, opt)
    # warmup / compile (2 structures: empty accs then full)
    step(ids, labels)
    step(ids, labels)
    loss = step(ids, labels)
    loss.numpy()

    iters = 15 if on_tpu else 3
    rounds = 3 if on_tpu else 1
    rates = []
    for _ in range(rounds):
        t0 = time.perf_counter()
        for _ in range(iters):
            loss = step(ids, labels)
        loss.numpy()  # sync
        dt = time.perf_counter() - t0
        rates.append(batch * seq * iters / dt)
    tokens_per_sec = float(np.median(rates))
    spread = (float(np.max(rates) - np.min(rates)) / tokens_per_sec
              if len(rates) > 1 else 0.0)

    # MFU: 6*N FLOPs per token (fwd+bwd) / bf16 peak
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    flops_per_token = 6 * n_params
    if on_tpu:
        peak = 197e12  # v5e bf16 peak (394e12 is int8)
        mfu = tokens_per_sec * flops_per_token / peak
    else:
        mfu = 0.0  # CPU fallback: MFU vs TPU peak is meaningless

    print(json.dumps({
        "metric": "gpt125m_train_tokens_per_sec_per_chip" if on_tpu
        else "gpt_tiny_cpu_tokens_per_sec",
        "value": round(tokens_per_sec, 2),
        "unit": "tokens/s",
        "vs_baseline": round(mfu, 4),  # true MFU fraction (bf16 peak)
        "spread_frac": round(spread, 4),
    }))


if __name__ == "__main__":
    main()
