"""Time fwd / fwd+bwd / full step through the framework, and a pure-JAX
hand-written GPT-125M train step as the XLA ceiling."""
import time, json
import numpy as np
import jax, jax.numpy as jnp


def sync(r):
    leaves = jax.tree.leaves(r)
    np.asarray(leaves[0])  # force device->host of one leaf

def timeit(f, *a, iters=20):
    r = f(*a); sync(r)
    r = f(*a); sync(r)
    t0 = time.perf_counter()
    for _ in range(iters):
        r = f(*a)
    sync(r)
    return (time.perf_counter() - t0) / iters * 1e3  # ms


B, S, V, H, L, NH, F = 8, 1024, 50304, 768, 12, 12, 3072


def framework():
    import paddle_tpu as paddle
    from paddle_tpu.jit import CompiledTrainStep, layer_state
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.core.dispatch import apply_op
    from paddle_tpu.core.tensor import Tensor

    cfg = GPTConfig.gpt3_125m(vocab_size=V, max_seq_len=S, dtype="bfloat16",
                              use_flash_attention=True)
    model = GPTForCausalLM(cfg)
    opt = paddle.optimizer.AdamW(1e-4, parameters=model.parameters())
    ids = paddle.randint(0, V, [B, S])
    labels = paddle.randint(0, V, [B, S])

    def loss_fn(m, x, l):
        logits = m(x)
        def fn(lg, lb):
            lg = lg.astype(jnp.float32)
            lse = jax.nn.logsumexp(lg, -1)
            picked = jnp.take_along_axis(
                lg, lb[..., None].astype(jnp.int32), -1)[..., 0]
            return jnp.mean(lse - picked)
        return apply_op("ce", fn, logits, l)

    ms_fwd = ms_fwdbwd = -1.0
    step = CompiledTrainStep(model, loss_fn, opt)
    step(ids, labels); step(ids, labels)
    t0 = time.perf_counter()
    for _ in range(20):
        loss = step(ids, labels)
    loss.numpy()
    ms_step = (time.perf_counter() - t0) / 20 * 1e3
    print(json.dumps({"which": "framework", "fwd_ms": round(ms_fwd, 2),
                      "fwdbwd_ms": round(ms_fwdbwd, 2),
                      "step_ms": round(ms_step, 2)}), flush=True)


def pure_jax():
    key = jax.random.PRNGKey(0)
    dt = jnp.bfloat16
    p = {
        "wte": jax.random.normal(key, (V, H), dt) * 0.02,
        "wpe": jax.random.normal(key, (S, H), dt) * 0.02,
        "ln1_w": jnp.ones((L, H), dt), "ln1_b": jnp.zeros((L, H), dt),
        "qkv_w": jax.random.normal(key, (L, H, 3 * H), dt) * 0.02,
        "qkv_b": jnp.zeros((L, 3 * H), dt),
        "proj_w": jax.random.normal(key, (L, H, H), dt) * 0.02,
        "proj_b": jnp.zeros((L, H), dt),
        "ln2_w": jnp.ones((L, H), dt), "ln2_b": jnp.zeros((L, H), dt),
        "fc1_w": jax.random.normal(key, (L, H, F), dt) * 0.02,
        "fc1_b": jnp.zeros((L, F), dt),
        "fc2_w": jax.random.normal(key, (L, F, H), dt) * 0.02,
        "fc2_b": jnp.zeros((L, H), dt),
        "lnf_w": jnp.ones((H,), dt), "lnf_b": jnp.zeros((H,), dt),
    }
    from paddle_tpu.kernels.flash_attention import flash_attention_fwd

    def norm(x, w, b):
        xf = x.astype(jnp.float32)
        m = jnp.mean(xf, -1, keepdims=True)
        v = jnp.var(xf, -1, keepdims=True)
        return ((xf - m) * jax.lax.rsqrt(v + 1e-5)).astype(x.dtype) * w + b

    def block(h, lw):
        x = norm(h, lw["ln1_w"], lw["ln1_b"])
        qkv = x @ lw["qkv_w"] + lw["qkv_b"]
        q, k, v = jnp.split(qkv, 3, -1)
        q = q.reshape(B, S, NH, H // NH); k = k.reshape(B, S, NH, H // NH)
        v = v.reshape(B, S, NH, H // NH)
        o = flash_attention_fwd(q, k, v, causal=True).reshape(B, S, H)
        h = h + o @ lw["proj_w"] + lw["proj_b"]
        x = norm(h, lw["ln2_w"], lw["ln2_b"])
        f = jax.nn.gelu(x @ lw["fc1_w"] + lw["fc1_b"]) @ lw["fc2_w"] + lw["fc2_b"]
        return h + f

    def loss_fn(p, ids, labels):
        h = p["wte"][ids] + p["wpe"][jnp.arange(S)]
        stack = {k: p[k] for k in ["ln1_w", "ln1_b", "qkv_w", "qkv_b",
                                   "proj_w", "proj_b", "ln2_w", "ln2_b",
                                   "fc1_w", "fc1_b", "fc2_w", "fc2_b"]}
        def body(h, lw):
            return block(h, lw), None
        h, _ = jax.lax.scan(body, h, stack)
        h = norm(h, p["lnf_w"], p["lnf_b"])
        lg = (h @ p["wte"].T).astype(jnp.float32)
        lse = jax.nn.logsumexp(lg, -1)
        picked = jnp.take_along_axis(lg, labels[..., None], -1)[..., 0]
        return jnp.mean(lse - picked)

    mstate = jax.tree.map(lambda x: jnp.zeros(x.shape, jnp.float32), p)
    vstate = jax.tree.map(lambda x: jnp.zeros(x.shape, jnp.float32), p)
    master = jax.tree.map(lambda x: x.astype(jnp.float32), p)

    @jax.jit
    def fwd(p, ids, labels):
        return loss_fn(p, ids, labels)

    @jax.jit
    def fwdbwd(p, ids, labels):
        return jax.value_and_grad(loss_fn)(p, ids, labels)

    def stepfn(p, master, m, v, ids, labels):
        loss, g = jax.value_and_grad(loss_fn)(p, ids, labels)
        g = jax.tree.map(lambda x: x.astype(jnp.float32), g)
        m = jax.tree.map(lambda m, g: 0.9 * m + 0.1 * g, m, g)
        v = jax.tree.map(lambda v, g: 0.999 * v + 0.001 * g * g, v, g)
        master = jax.tree.map(
            lambda w, m, v: w - 1e-4 * (m / (jnp.sqrt(v) + 1e-8) + 0.01 * w),
            master, m, v)
        p = jax.tree.map(lambda w, x: w.astype(x.dtype), master, p)
        return loss, p, master, m, v
    jstep = jax.jit(stepfn, donate_argnums=(0, 1, 2, 3))

    ids = jax.random.randint(key, (B, S), 0, V)
    labels = jax.random.randint(key, (B, S), 0, V)
    ms_fwd = timeit(fwd, p, ids, labels)
    ms_fwdbwd = timeit(fwdbwd, p, ids, labels)
    # step donates, so loop manually
    loss, p2, master, mstate, vstate = jstep(p, master, mstate, vstate, ids, labels)
    loss, p2, master, mstate, vstate = jstep(p2, master, mstate, vstate, ids, labels)
    t0 = time.perf_counter()
    for _ in range(20):
        loss, p2, master, mstate, vstate = jstep(p2, master, mstate, vstate,
                                                 ids, labels)
    np.asarray(loss)
    ms_step = (time.perf_counter() - t0) / 20 * 1e3
    print(json.dumps({"which": "pure_jax", "fwd_ms": round(ms_fwd, 2),
                      "fwdbwd_ms": round(ms_fwdbwd, 2),
                      "step_ms": round(ms_step, 2)}), flush=True)


if __name__ == "__main__":
    pure_jax()
    framework()
