#!/usr/bin/env python
"""TPU-hazard lint driver (rules PT001–PT006; see paddle_tpu/analysis/lint.py).

Usage:
  python scripts/lint_tpu.py                # report all findings
  python scripts/lint_tpu.py --check        # CI gate: fail on NEW findings
  python scripts/lint_tpu.py --update-baseline
  python scripts/lint_tpu.py --json         # machine-readable output
  python scripts/lint_tpu.py path.py ...    # lint specific files

``--check`` compares active (non-suppressed) findings against
``scripts/lint_baseline.json`` by stable fingerprint and exits nonzero if
anything new appears (or if baselined entries are plain missing — stale
baselines are debt too).  The goal state is an empty baseline: every
intentional hazard carries an inline ``# ptlint: disable=PTNNN
reason="..."`` instead.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, ROOT)

from paddle_tpu.analysis import lint  # noqa: E402

BASELINE = os.path.join(ROOT, "scripts", "lint_baseline.json")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*",
                    help="files to lint (default: paddle_tpu/ + scripts/)")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 on findings not in the baseline")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite scripts/lint_baseline.json from findings")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="emit findings as JSON")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also print suppressed findings")
    args = ap.parse_args(argv)

    paths = args.paths or lint.default_targets(ROOT)
    findings = lint.lint_paths(paths, root=ROOT)
    active = [f for f in findings if not f.suppressed]
    suppressed = [f for f in findings if f.suppressed]

    if args.as_json:
        print(json.dumps([{
            "rule": f.rule, "path": f.path, "line": f.line, "col": f.col,
            "message": f.message, "suppressed": f.suppressed,
            "reason": f.reason, "fingerprint": lint.fingerprint(f),
        } for f in findings], indent=2))
    else:
        shown = findings if args.show_suppressed else active
        for f in shown:
            print(f.format())
        print(f"ptlint: {len(active)} active finding(s), "
              f"{len(suppressed)} suppressed, {len(paths)} file(s)")

    if args.update_baseline:
        lint.save_baseline(BASELINE, findings)
        print(f"ptlint: wrote baseline ({len(active)} entries) -> {BASELINE}")
        return 0

    if args.check:
        baseline = lint.load_baseline(BASELINE)
        new = [f for f in active if lint.fingerprint(f) not in baseline]
        fixed = baseline - {lint.fingerprint(f) for f in active}
        if new:
            print(f"ptlint: {len(new)} NEW finding(s) not in baseline:")
            for f in new:
                print("  " + f.format())
            return 1
        if fixed:
            print(f"ptlint: {len(fixed)} baseline entr(ies) no longer "
                  "fire — run --update-baseline to shed the debt")
            return 1
        print("ptlint: check OK (no new findings)")
        return 0

    return 1 if active and not args.paths else 0


if __name__ == "__main__":
    sys.exit(main())
