#!/usr/bin/env python
"""Perf-regression detector over the BENCH_r0*.json trajectory.

The bench driver appends one ``BENCH_r<NN>.json`` per run (the parsed
flagship metric plus per-leg detail under ``parsed["legs"]``).  This
script is the pre-merge perf gate over that trajectory: it compares a
**candidate** run (the newest file by default, or ``--candidate
path.json``) against the **best prior** value of every (leg, metric)
pair and exits non-zero when any metric regressed past its tolerance.

Comparison model
----------------
* Leg dicts are flattened to dotted metric paths (``ttft.p95_ms``,
  ``tokens_per_sec``), keeping only numeric leaves.
* Each metric is classified by name: throughput-like (``tokens_per_sec``,
  ``mfu``, ``capacity_ratio``, ``goodput``, hit/acceptance rates) must
  not DROP; latency-like (``ttft``/``itl``/``queue_wait``/``*_ms``/
  ``p50/p95/p99``/``step_time``) must not RISE.  Unclassified metrics
  (counts, spread fractions) are informational only.
* "Best prior" is the max (throughput) / min (latency) over every
  earlier run that has the metric — a candidate is held to the best the
  trajectory has ever shown, not just the previous run, so a slow decay
  across several PRs cannot hide.
* Tolerance is relative: candidate < best * (1 - tol) (throughput) or
  candidate > best * (1 + tol) (latency) is a regression.  Default
  ``--tol 0.1``; per-metric overrides with ``--tol-for ttft.p95_ms=0.25``
  (suffix match, longest wins).

Runs whose command failed (``rc != 0``) or produced nothing parseable
are skipped (the r01 bootstrap run predates the CPU-safe bench).  Legacy
runs without ``legs`` contribute their flagship parsed metric under the
synthetic leg ``_flagship``.

Usage::

    python scripts/bench_compare.py                   # newest vs history
    python scripts/bench_compare.py --candidate out.json --json
    python scripts/bench_compare.py --tol 0.15 --tol-for mfu=0.05
    python scripts/bench_compare.py --attribute       # per-program
                                                      # device-time diff

Exit status: 0 clean, 1 regression(s), 2 not enough data to compare.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

_HIGHER = ("tokens_per_sec", "mfu", "capacity_ratio", "goodput",
           "hit_rate", "acceptance", "retention", "vs_baseline",
           "tenants_per")
_LOWER_RE = re.compile(
    r"(ttft|itl|queue_wait|latency|step_time|save|restore)"
    r"|(_ms$)|(^|\.)(p50|p95|p99|mean)(_ms)?$")
# traffic volumes, not performance: tier spill/restore block counts vary
# with scheduling order (and "restored_blocks" would otherwise trip the
# latency-ish "restore" token above)
_SKIP_RE = re.compile(
    r"(^|\.)(count|spread_frac|n_params|spilled_blocks|restored_blocks"
    r"|host_buf_reuse|readopted|sheds)($|\.)")
# per-program device-time ledger blocks embedded by bench legs
# (devicetime.programs.<name>.<field>): a program's share of device time
# and its mean/p95 latency must not RISE, its MFU must not DROP;
# everything else in the block (sample_every, est_total_s, tflops — all
# window-length- or host-load-dependent) is informational
_DT_RE = re.compile(r"(^|\.)devicetime\.")
_DT_PROG_PREFIX = "devicetime.programs."


def classify(metric):
    """'higher' / 'lower' / None (informational) for one dotted path."""
    if _SKIP_RE.search(metric):
        return None
    if _DT_RE.search(metric):
        if _DT_PROG_PREFIX not in metric:
            return None
        if metric.endswith(".share") or metric.endswith("_ms"):
            return "lower"
        if metric.endswith(".mfu"):
            return "higher"
        return None
    if any(tok in metric for tok in _HIGHER):
        return "higher"
    if _LOWER_RE.search(metric):
        return "lower"
    return None


def _flatten(obj, prefix=""):
    out = {}
    if isinstance(obj, dict):
        for k, v in obj.items():
            out.update(_flatten(v, f"{prefix}{k}." if prefix or True
                                else k))
    elif isinstance(obj, bool):
        pass
    elif isinstance(obj, (int, float)):
        out[prefix[:-1]] = float(obj)
    return out


def extract(run):
    """``{leg: {metric: value}}`` from one BENCH json dict (None when
    the run carries nothing comparable)."""
    if run.get("rc") not in (0, None):
        return None
    parsed = run.get("parsed")
    if not isinstance(parsed, dict):
        return None
    legs = parsed.get("legs")
    out = {}
    if isinstance(legs, dict):
        for leg, detail in legs.items():
            if isinstance(detail, dict):
                out[leg] = _flatten(detail)
    else:
        # legacy flagship-only run: "gpt125m_train_tokens_per_sec_per_chip"
        # becomes leg "gpt125m" metric "tokens_per_sec" (vs_baseline is
        # the MFU fraction on the train legs) so the trajectory stays
        # comparable across the schema change
        name = str(parsed.get("metric", ""))
        m = re.match(r"([A-Za-z0-9]+)_train_tokens_per_sec", name)
        leg = m.group(1) if m else "_flagship"
        flat = {}
        if isinstance(parsed.get("value"), (int, float)):
            flat["tokens_per_sec"] = float(parsed["value"])
        if isinstance(parsed.get("vs_baseline"), (int, float)):
            flat["mfu" if m else "vs_baseline"] = \
                float(parsed["vs_baseline"])
        if flat:
            out[leg] = flat
    return out or None


def load_history(pattern):
    runs = []
    for path in sorted(glob.glob(pattern)):
        try:
            with open(path) as f:
                d = json.load(f)
        except (OSError, ValueError):
            continue
        legs = extract(d)
        if legs is not None:
            runs.append({"path": path, "n": d.get("n"), "legs": legs})
    return runs


def tol_for(metric, default, overrides):
    """Longest-suffix-match tolerance override for one metric path."""
    best_len, best = -1, default
    for suffix, t in overrides.items():
        if (metric == suffix or metric.endswith("." + suffix)
                or metric.endswith(suffix)) and len(suffix) > best_len:
            best_len, best = len(suffix), t
    return best


def compare(history, candidate, default_tol, overrides):
    """Candidate legs vs best prior per (leg, metric).  Returns
    (regressions, checks) — ``checks`` is every comparison made."""
    best = {}           # (leg, metric) -> (value, path)
    for run in history:
        for leg, metrics in run["legs"].items():
            for m, v in metrics.items():
                direction = classify(m)
                if direction is None:
                    continue
                key = (leg, m)
                cur = best.get(key)
                better = (cur is None
                          or (direction == "higher" and v > cur[0])
                          or (direction == "lower" and v < cur[0]))
                if better:
                    best[key] = (v, run["path"])
    checks, regressions = [], []
    for leg, metrics in candidate["legs"].items():
        for m, v in sorted(metrics.items()):
            direction = classify(m)
            if direction is None or (leg, m) not in best:
                continue
            bv, bpath = best[(leg, m)]
            tol = tol_for(m, default_tol, overrides)
            if direction == "higher":
                limit = bv * (1.0 - tol)
                bad = v < limit
            else:
                limit = bv * (1.0 + tol)
                bad = v > limit
            rec = {"leg": leg, "metric": m, "direction": direction,
                   "candidate": v, "best_prior": bv,
                   "best_prior_run": os.path.basename(bpath),
                   "tolerance": tol, "limit": limit,
                   "regressed": bad}
            checks.append(rec)
            if bad:
                regressions.append(rec)
    return regressions, checks


def _dt_shares(metrics):
    """``{program: share}`` from one leg's flattened metric paths."""
    out = {}
    for m, v in metrics.items():
        if (m.startswith(_DT_PROG_PREFIX)
                and m.endswith(".share")):
            out[m[len(_DT_PROG_PREFIX):-len(".share")]] = v
    return out


def attribute(prior, candidate, regressions):
    """Per-leg device-time attribution: for every candidate leg carrying
    a devicetime block, diff each program's share of device time against
    the most recent prior run that also carries one, and rank the
    movers.  A regressed leg is thereby NAMED the program(s) whose share
    moved — the diagnosis the perf gate hands to the real-chip
    campaign."""
    regressed_legs = {r["leg"] for r in regressions}
    out = []
    for leg, metrics in sorted(candidate["legs"].items()):
        shares = _dt_shares(metrics)
        if not shares:
            continue
        base, base_path = None, None
        for run in reversed(prior):
            pm = run["legs"].get(leg)
            if pm:
                ps = _dt_shares(pm)
                if ps:
                    base, base_path = ps, run["path"]
                    break
        movers = []
        for prog in set(shares) | set(base or {}):
            c = shares.get(prog, 0.0)
            b = (base or {}).get(prog, 0.0)
            movers.append({"program": prog, "share": round(c, 4),
                           "prior_share": round(b, 4),
                           "moved": round(c - b, 4)})
        movers.sort(key=lambda m: abs(m["moved"]), reverse=True)
        dominant = max(movers, key=lambda m: m["share"])
        out.append({"leg": leg,
                    "baseline_run": (os.path.basename(base_path)
                                     if base_path else None),
                    "regressed": leg in regressed_legs,
                    "dominant": dominant["program"],
                    "dominant_share": dominant["share"],
                    "movers": movers[:5]})
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="BENCH trajectory perf-regression gate")
    ap.add_argument("--glob", default="BENCH_r0*.json",
                    help="history file pattern (default: BENCH_r0*.json "
                         "in the repo root / cwd)")
    ap.add_argument("--candidate", default=None,
                    help="candidate run json (default: the newest "
                         "history file; it is then excluded from the "
                         "prior set)")
    ap.add_argument("--tol", type=float, default=0.1,
                    help="default relative tolerance (default 0.1)")
    ap.add_argument("--tol-for", action="append", default=[],
                    metavar="METRIC=FRAC",
                    help="per-metric tolerance override (suffix match), "
                         "repeatable")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable report on stdout")
    ap.add_argument("--attribute", action="store_true",
                    help="per-program device-time attribution: name the "
                         "program(s) whose share of device time moved, "
                         "per leg (needs devicetime blocks in the runs)")
    args = ap.parse_args(argv)

    overrides = {}
    for spec in args.tol_for:
        name, _, frac = spec.partition("=")
        try:
            overrides[name] = float(frac)
        except ValueError:
            ap.error(f"bad --tol-for {spec!r} (want METRIC=FRAC)")

    history = load_history(args.glob)
    if args.candidate:
        try:
            with open(args.candidate) as f:
                d = json.load(f)
        except (OSError, ValueError) as e:
            print(f"bench_compare: cannot read candidate "
                  f"{args.candidate}: {e}", file=sys.stderr)
            return 2
        legs = extract(d)
        if legs is None:
            print("bench_compare: candidate run has no comparable "
                  "metrics", file=sys.stderr)
            return 2
        candidate = {"path": args.candidate, "n": d.get("n"),
                     "legs": legs}
        prior = [r for r in history
                 if os.path.abspath(r["path"])
                 != os.path.abspath(args.candidate)]
    else:
        if len(history) < 2:
            print("bench_compare: need >= 2 comparable runs "
                  f"(found {len(history)} under {args.glob!r})",
                  file=sys.stderr)
            return 2
        candidate, prior = history[-1], history[:-1]

    if not prior:
        print("bench_compare: no prior runs to compare against",
              file=sys.stderr)
        return 2

    regressions, checks = compare(prior, candidate, args.tol, overrides)
    report = {"candidate": os.path.basename(candidate["path"]),
              "prior_runs": [os.path.basename(r["path"]) for r in prior],
              "checks": checks,
              "regressions": regressions,
              "value": len(regressions)}
    attribution = (attribute(prior, candidate, regressions)
                   if args.attribute else None)
    if attribution is not None:
        report["attribution"] = attribution
    if args.json:
        print(json.dumps(report, indent=1))
    else:
        print(f"candidate {report['candidate']} vs "
              f"{len(prior)} prior run(s):")
        for c in checks:
            mark = "REGRESSED" if c["regressed"] else "ok"
            arrow = ">" if c["direction"] == "higher" else "<"
            print(f"  [{mark:>9}] {c['leg']}.{c['metric']}: "
                  f"{c['candidate']:g} (best {c['best_prior']:g} in "
                  f"{c['best_prior_run']}, need {arrow}= "
                  f"{c['limit']:g})")
        if not checks:
            print("  (no overlapping gated metrics)")
        if attribution is not None:
            print("device-time attribution:")
            if not attribution:
                print("  (no devicetime blocks in the candidate legs)")
            for a in attribution:
                base = (f"vs {a['baseline_run']}" if a["baseline_run"]
                        else "no prior devicetime block")
                mark = "REGRESSED " if a["regressed"] else ""
                print(f"  {mark}{a['leg']} ({base}): dominant program "
                      f"{a['dominant']} at {a['dominant_share']:.1%} of "
                      "device time")
                for m in a["movers"]:
                    if m["moved"]:
                        print(f"    {m['program']}: share "
                              f"{m['prior_share']:.1%} -> "
                              f"{m['share']:.1%} "
                              f"({m['moved']:+.1%})")
        print(f"{len(regressions)} regression(s)")
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
