"""Counter-verified steady-state gate: a short CompiledTrainStep run must
reach a zero-python-overhead steady state, proven by the process-global
``paddle_tpu.profiler.counters`` registry rather than by timing.

Protocol: 2 warmup steps (step 1 hydrates + traces, step 2 retraces once —
the optimizer accumulators change the carried-state structure), then 2
measured steps which must show:

  * 0 retraces           (jit.traces — the python step body never re-runs)
  * 0 rehydrations       (jit.hydrates)
  * 0 host bind/sync work (jit.host.*, jit.syncs)
  * 2 cache hits, 0 misses (every dispatch is a pure jit-cache hit)

A second phase gates the fused multi-step dispatch path
(``fused_steps=K``): after its warmup (window 1 = priming single-step
fallback, window 2 = scan compile), every measured K-step window must be
exactly ONE XLA dispatch — ``jit.host.dispatches == jit.steps / K`` —
again with zero retraces / rehydrates / host binds.

A third phase gates the serving engine (``paddle_tpu.serving.LLMEngine``):
warmup requests compile one prefill/insert program per power-of-two
bucket plus the single decode program; measured requests that reuse those
buckets must show ``serving.retraces == 0`` and zero jit.* trace/hydrate/
host-bind movement — continuous batching reaches the same
zero-python-overhead steady state as training.

A fourth phase gates the elastic serving fleet
(``paddle_tpu.serving.ServingFleet``): the no-fault fleet must be
token-identical to the single engine with zero steady-state retraces
(``warm_buckets`` pre-compiles every replica), and a churn run under a
deterministic ``replica_crash`` schedule must show
``serving.fleet.lost == 0`` with ``respawns``/``retried`` equal to the
injected fault count — zero lost requests under churn.

A fifth phase gates checkpointed training (``paddle_tpu.resilience``):
a warm step interleaved with ``CheckpointManager.save`` calls must show
zero retraces/rehydrates and zero host sync work beyond the ONE
counter-gated ``sync()`` per save (``jit.syncs == saves``, with exactly
one ``bind_layer_state``/``bind_optimizer_state`` pair each and zero
``layer_state``/``optimizer_state`` re-reads); then a
``FaultTolerantTrainer`` run under a deterministic fault schedule must
show ``resilience.restores == injected preemptions``.

A sixth phase gates the multi-chip SPMD mesh path
(``CompiledTrainStep(mesh=...)``): on >=4 devices (forced host devices in
CI) a 2x2 dp/mp mesh with a ``shard_rules`` tensor-parallel split must
prove its weights actually live sharded (local shard shape check), reach
the SAME steady-state economics as the single-device path — zero
retraces / rehydrates / host binds, ``dispatches == MEASURE``, and
``dist.collective_launches == 0`` (GSPMD collectives are compiled into
the program, never host-issued) — and the fused-on-mesh run must keep
``dispatches == steps/K``.

A seventh phase gates the telemetry subsystem's zero-overhead claim
(``profiler.metrics``): every steady-state phase above (train, fused,
mesh dp2, serving) is run twice with fresh objects — metrics OFF, then
metrics ON (``CompiledTrainStep(metrics=True)``; telemetry harvested
inside the measured window) — and the ``jit.syncs`` / ``jit.traces`` /
``jit.host.dispatches`` / ``serving.retraces`` deltas must be IDENTICAL:
in-graph metric accumulation and host-side harvesting add zero syncs,
zero retraces, zero extra dispatches.

An eighth phase gates request tracing (``profiler.trace``) the same two
ways: with ``FLAGS_request_trace_sample=0`` a fresh serving + paged +
fleet workload must move ZERO ``trace.*`` counters and must be
counter-identical (same parity keys: zero extra retraces / hydrates /
host dispatches / syncs) to the tracing-ON run of the identical
workload; with sample=1, every finished engine request's stage spans
(queue + prefill + decode) must sum within tolerance of its measured
TTFT + decode wall clock — the span tree accounts for the latency the
histograms report.

A ninth phase gates speculative decoding
(``LLMEngine(draft_model=...)``): greedy speculative output must be
token-identical to the non-speculative paged engine, a warm measured
window must dispatch only cached programs (zero retraces / traces /
hydrates / syncs) while the engine's whole lifetime compiled exactly
ONE draft decode + ONE verify program, and the acceptance ledger must
balance exactly — ``serving.spec.accepted + rejected == drafted`` with
K+1 draft launches + ONE verify launch per round.  The program-audit
phase additionally serves through a speculative engine under
``FLAGS_program_audit=enforce`` with OFF/ON counter parity.

A tenth phase gates the disaggregated prefill/decode split
(``ServingFleet(prefill_replicas=...)``): a 1+1 split must be
token-identical to the unified paged fleet on the same prompts, every
hand-off must copy EXACTLY the owned non-shared KV blocks
(``serving.fleet.migrate.blocks_copied`` equals the block-table size
minus the block-aligned prefix resolved against the decode replica's
radix tree — a shared prefix is never moved twice), and the measured
hand-offs must retrace nothing once the warm pass has compiled the
migration gather.

An eleventh phase gates the host-RAM KV tier
(``LLMEngine(host_kv_blocks=...)``): a paged engine whose block pool is
far smaller than its working set must stay token-identical — greedy AND
seeded sampling — to the ample-pool engine while cold prefix chains
spill to pinned host buffers and page back on demand; the measured
spill/restore churn must retrace/trace/sync NOTHING and must not grow
the host arena (every buffer comes from the reuse pool:
``serving.kv.host_buf_reuse`` moves, ``serving.kv.host_arena_bytes``
does not); and a ``kv_spill_drop`` fault mid-restore must degrade to a
deterministic cache-miss replay with identical tokens and a reconciled
block pool.

A twelfth phase gates the device-time ledger
(``profiler.devicetime``): with ``FLAGS_device_time_sample=0`` a fresh
slot + paged + speculative workload must move ZERO ``jit.devicetime.*``
/ ``program.*`` state and be counter-identical on the parity keys to
the sampling-ON run of the identical workload; with sample=4 the
measured window must pay EXACTLY ``ceil(dispatches / 4)`` sampled
block-until-ready fences (``jit.devicetime.sampled_syncs``) with token
identity and zero retraces, and the ledger it leaves behind must carry
MFU/roofline gauges that survive ``GET /programs`` and a
``bench_compare.py --attribute`` run that names the dominant program.

A thirteenth phase gates tensor-parallel serving over the StateArena
(``serving.arena``): an mp2 paged engine must be token-identical to the
single-device engine (greedy AND seeded) with the zero-steady-retrace
economics and dispatch counts unchanged, the KV pool genuinely
head-sharded per chip, and every cross-chip reduction an in-graph
collective under the auditor's compiled-HLO census.

A fourteenth phase gates multi-tenant LoRA serving
(``serving.adapters``): ONE compiled decode program serves any tenant
mix — a heterogeneous batch (three tenants + a base row in the same
decode step) must be token-identical to running each tenant
sequentially, base-only traffic through an adapter engine must match
the adapter-free twin row for row, the warm steady window must move
ZERO retraces/hydrates/syncs/arena-misses with dispatch counts equal to
the adapter-free reference, and an eviction-then-reuse cycle (more
tenants than arena slots) must page the evicted tenant back in warm —
``serving.adapter.loads`` moves, programs never retrace, tokens never
change.

Prints one JSON line; raises AssertionError on any violation.  Wired as a
tier-1 test via tests/test_profiler.py.  Run directly:
``python scripts/check_counters.py``.
"""

import json
import os
import time

WARMUP = 2
MEASURE = 2
FUSED_K = 2
FUSED_MEASURE = 2  # measured windows = FUSED_MEASURE * FUSED_K steps
SERVE_LENS_WARM = (3, 6)      # buckets {4, 8} with min_bucket=4
SERVE_LENS_MEASURE = (4, 5)   # same buckets — must retrace NOTHING


def run():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # the mesh gate needs >1 device; only effective before the first jax
    # import (tests/conftest.py sets the same flag), no-op on real TPUs
    if ("--xla_force_host_platform_device_count"
            not in os.environ.get("XLA_FLAGS", "")):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8").strip()
    import paddle_tpu as paddle
    import paddle_tpu.jit as pjit
    import paddle_tpu.nn as nn
    from paddle_tpu.profiler import counters

    paddle.seed(0)
    model = nn.Sequential(nn.Linear(16, 32), nn.GELU(), nn.Linear(32, 4))
    opt = paddle.optimizer.AdamW(1e-3, parameters=model.parameters())
    x = paddle.randn([8, 16])
    y = paddle.randn([8, 4])

    def loss_fn(m, a, b):
        return ((m(a) - b) ** 2).mean()

    step = pjit.CompiledTrainStep(model, loss_fn, opt)
    for _ in range(WARMUP):
        step(x, y).numpy()
    before = counters.snapshot()
    for _ in range(MEASURE):
        step(x, y).numpy()
    steady = counters.delta(before)

    invariants = {
        "jit.traces": 0,
        "jit.hydrates": 0,
        "jit.syncs": 0,
        "jit.cache_misses": 0,
        "jit.cache_hits": MEASURE,
        "jit.steps": MEASURE,
        "jit.host.dispatches": MEASURE,  # single-step mode: 1 launch/step
    }
    invariants.update({"jit.host." + k: 0 for k in pjit._HOST_SYNC_KEYS})

    violations = {k: (steady.get(k, 0), want)
                  for k, want in invariants.items()
                  if steady.get(k, 0) != want}

    # ---- fused multi-step dispatch gate: dispatches == steps / K --------
    from paddle_tpu.io import Window

    paddle.seed(0)
    fmodel = nn.Sequential(nn.Linear(16, 32), nn.GELU(), nn.Linear(32, 4))
    fopt = paddle.optimizer.AdamW(1e-3, parameters=fmodel.parameters())
    fstep = pjit.CompiledTrainStep(fmodel, loss_fn, fopt,
                                   fused_steps=FUSED_K)
    import numpy as np
    rng = np.random.RandomState(0)
    def window():
        return Window(
            (paddle.to_tensor(rng.randn(FUSED_K, 8, 16).astype("float32")),
             paddle.to_tensor(rng.randn(FUSED_K, 8, 4).astype("float32"))),
            FUSED_K)
    fstep(window()).numpy()  # window 1: priming single-step fallback
    fstep(window()).numpy()  # window 2: scan compile
    fbefore = counters.snapshot()
    for _ in range(FUSED_MEASURE):
        fstep(window()).numpy()
    fsteady = counters.delta(fbefore)

    finvariants = {
        "jit.traces": 0,
        "jit.hydrates": 0,
        "jit.syncs": 0,
        "jit.cache_misses": 0,
        "jit.cache_hits": FUSED_MEASURE,
        "jit.steps": FUSED_MEASURE * FUSED_K,
        "jit.fused_windows": FUSED_MEASURE,
        "jit.fused_fallback_steps": 0,
        # THE fused-dispatch economics gate: one launch per K-step window
        "jit.host.dispatches": (FUSED_MEASURE * FUSED_K) // FUSED_K,
    }
    finvariants.update({"jit.host." + k: 0 for k in pjit._HOST_SYNC_KEYS})
    violations.update({f"fused:{k}": (fsteady.get(k, 0), want)
                       for k, want in finvariants.items()
                       if fsteady.get(k, 0) != want})

    # ---- mesh gate: the multi-chip SPMD path keeps the same economics ---
    import jax
    if jax.device_count() >= 4:
        from jax.sharding import Mesh, PartitionSpec as P

        mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2),
                    ("dp", "mp"))
        paddle.seed(0)
        mmodel = nn.Sequential(nn.Linear(16, 32), nn.GELU(),
                               nn.Linear(32, 4))
        mopt = paddle.optimizer.AdamW(1e-3,
                                      parameters=mmodel.parameters())
        mstep = pjit.CompiledTrainStep(
            mmodel, loss_fn, mopt, mesh=mesh,
            shard_rules=[(r"\.weight$", P(None, "mp"))])
        for _ in range(WARMUP):
            mstep(x, y).numpy()
        # sharded-placement proof: the (16, 32) Linear weight split over
        # mp=2 must live as (16, 16) local shards, not a replicated copy.
        # The live weights sit in the donated carry (mstep._state), not in
        # the model's stale host-bound params.
        w = next(v for v in jax.tree_util.tree_leaves(mstep._state[0])
                 if tuple(v.shape) == (16, 32))
        shard_shape = tuple(w.addressable_shards[0].data.shape)
        if shard_shape != (16, 16):
            violations["mesh:weight_shard_shape"] = (shard_shape,
                                                     (16, 16))
        mbefore = counters.snapshot()
        for _ in range(MEASURE):
            mstep(x, y).numpy()
        msteady = counters.delta(mbefore)
        minvariants = dict(invariants)
        # GSPMD collectives are compiled into the step program — the
        # steady state must issue ZERO host-side collective launches
        minvariants["dist.collective_launches"] = 0
        violations.update({f"mesh:{k}": (msteady.get(k, 0), want)
                           for k, want in minvariants.items()
                           if msteady.get(k, 0) != want})

        # fused-on-mesh: one XLA launch per K-step window, same as the
        # single-device fused gate
        paddle.seed(0)
        fmmodel = nn.Sequential(nn.Linear(16, 32), nn.GELU(),
                                nn.Linear(32, 4))
        fmopt = paddle.optimizer.AdamW(1e-3,
                                       parameters=fmmodel.parameters())
        fmstep = pjit.CompiledTrainStep(
            fmmodel, loss_fn, fmopt, fused_steps=FUSED_K, mesh=mesh,
            shard_rules=[(r"\.weight$", P(None, "mp"))])
        fmstep(window()).numpy()  # priming single-step fallback
        fmstep(window()).numpy()  # scan compile
        fmbefore = counters.snapshot()
        for _ in range(FUSED_MEASURE):
            fmstep(window()).numpy()
        fmsteady = counters.delta(fmbefore)
        fminvariants = dict(finvariants)
        fminvariants["dist.collective_launches"] = 0
        violations.update({f"mesh-fused:{k}": (fmsteady.get(k, 0), want)
                           for k, want in fminvariants.items()
                           if fmsteady.get(k, 0) != want})
    else:
        msteady = {"skipped":
                   f"needs 4 devices, have {jax.device_count()}"}
        fmsteady = msteady

    # ---- serving steady-state gate: warm buckets never retrace ----------
    from paddle_tpu.models import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import LLMEngine

    paddle.seed(0)
    scfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                     num_heads=4, max_seq_len=32,
                     use_flash_attention=False)
    smodel = GPTForCausalLM(scfg)
    smodel.eval()
    eng = LLMEngine(smodel, max_slots=2, max_seq_len=32, min_bucket=4)
    rng = np.random.RandomState(7)

    def serve(lens):
        hs = [eng.add_request(rng.randint(0, 64, size=n).tolist(),
                              max_new_tokens=3) for n in lens]
        while not all(h.is_finished for h in hs):
            eng.step()

    serve(SERVE_LENS_WARM)  # compiles prefill/insert per bucket + decode
    sbefore = counters.snapshot()
    serve(SERVE_LENS_MEASURE)
    ssteady = counters.delta(sbefore)

    sinvariants = {
        "serving.retraces": 0,
        "jit.traces": 0,
        "jit.hydrates": 0,
        "jit.syncs": 0,
        "serving.requests": len(SERVE_LENS_MEASURE),
        "serving.evictions": len(SERVE_LENS_MEASURE),
    }
    sinvariants.update({"jit.host." + k: 0 for k in pjit._HOST_SYNC_KEYS})
    violations.update({f"serving:{k}": (ssteady.get(k, 0), want)
                       for k, want in sinvariants.items()
                       if ssteady.get(k, 0) != want})

    # ---- paged-KV gate: fixed block tables never retrace ----------------
    # Same workload discipline as the serving gate, against the paged
    # engine: block tables are int32 OPERANDS, so the warm chunk buckets
    # + ONE decode program + ONE COW copy program must cover the measure
    # window with zero retraces/hydrates/host binds.
    peng = LLMEngine(smodel, max_slots=2, max_seq_len=32, min_bucket=4,
                     kv_layout="paged", block_size=4, prefill_chunk=8)

    def pserve(eng_, lens):
        hs = [eng_.add_request(rng.randint(0, 64, size=n).tolist(),
                               max_new_tokens=3) for n in lens]
        while not all(h.is_finished for h in hs):
            eng_.step()
        return hs

    ph0 = pserve(peng, SERVE_LENS_WARM)[0]
    # warm the copy-on-write program too: extend a sequence the warm
    # requests left in the prefix tree past its cached partial block
    cow_warm = (list(ph0.prompt) + ph0.tokens)[:5] + [int(ph0.prompt[0])]
    pserve_cow = peng.add_request(cow_warm, max_new_tokens=3)
    while not pserve_cow.is_finished:
        peng.step()

    pbefore = counters.snapshot()
    phs = pserve(peng, SERVE_LENS_MEASURE)
    psteady = counters.delta(pbefore)
    pinvariants = {
        "serving.retraces": 0,
        "jit.traces": 0,
        "jit.hydrates": 0,
        "jit.syncs": 0,
        "serving.requests": len(SERVE_LENS_MEASURE),
        "serving.evictions": len(SERVE_LENS_MEASURE),
    }
    pinvariants.update({"jit.host." + k: 0 for k in pjit._HOST_SYNC_KEYS})
    violations.update({f"paged:{k}": (psteady.get(k, 0), want)
                       for k, want in pinvariants.items()
                       if psteady.get(k, 0) != want})
    for h in phs:   # paged output must equal sequential generate
        pref = np.asarray(smodel.generate(
            paddle.to_tensor(np.asarray([list(h.prompt)])),
            max_new_tokens=3).numpy())[0][len(h.prompt):].tolist()
        if h.tokens != pref:
            violations[f"paged:identity@{h.rid}"] = (h.tokens, pref)

    # shared-prefix leg: against a no-cache twin serving the SAME
    # workload, the prefix cache must score hits and launch strictly
    # fewer prefill chunks
    psys = rng.randint(0, 64, size=12).tolist()
    ptails = [rng.randint(0, 64, size=4).tolist() for _ in range(3)]
    pnc = LLMEngine(smodel, max_slots=2, max_seq_len=32, min_bucket=4,
                    kv_layout="paged", block_size=4, prefill_chunk=8,
                    prefix_cache=False)
    ncbefore = counters.snapshot()
    for t in ptails:
        h = pnc.add_request(psys + t, max_new_tokens=3)
        while not h.is_finished:
            pnc.step()
    nc_chunks = counters.delta(ncbefore).get("serving.kv.prefill_chunks", 0)
    pc = LLMEngine(smodel, max_slots=2, max_seq_len=32, min_bucket=4,
                   kv_layout="paged", block_size=4, prefill_chunk=8)
    pcbefore = counters.snapshot()
    for t in ptails:    # sequential, so each finish feeds the tree
        h = pc.add_request(psys + t, max_new_tokens=3)
        while not h.is_finished:
            pc.step()
    pcdelta = counters.delta(pcbefore)
    pc_chunks = pcdelta.get("serving.kv.prefill_chunks", 0)
    pc_hits = pcdelta.get("serving.kv.prefix_hits", 0)
    if pc_hits < 2:
        violations["paged-prefix:hits"] = (pc_hits, ">=2")
    if not pc_chunks < nc_chunks:
        violations["paged-prefix:chunks"] = (pc_chunks, f"<{nc_chunks}")

    # ---- paged Pallas-kernel + quantized-KV gate ------------------------
    # The fused Pallas decode kernel (interpret mode on CPU) and the int8
    # arena must be drop-in twins of the plain-XLA paged engine: pallas is
    # TOKEN-identical (greedy and seeded sampling), quantized KV / PTQ
    # weights hold the documented logit-tolerance gate
    # (max |drift| <= 5% of the fp32 logit magnitude), and both keep the
    # steady-state economics — distinct program-cache keys, ONE decode
    # program per backend (kernels.paged.* tick once, at trace time), and
    # zero retraces in a warm measure window.
    import jax.numpy as jnp
    from paddle_tpu.core import flags as pflags
    from paddle_tpu.kernels import paged_attention as _pa
    from paddle_tpu.quantization import ptq_int8_decode_state

    pq_prompts = [rng.randint(0, 64, size=n).tolist() for n in (5, 9)]
    pq_sample = dict(do_sample=True, temperature=0.9, top_k=8)

    def pq_engine(**kw):
        return LLMEngine(smodel, max_slots=2, max_seq_len=32, min_bucket=4,
                         kv_layout="paged", block_size=4, prefill_chunk=8,
                         **kw)

    def pq_run(eng_, sampled=False):
        hs = [eng_.add_request(p, max_new_tokens=3, seed=21 + i,
                               **(pq_sample if sampled else {}))
              for i, p in enumerate(pq_prompts)]
        while not all(h.is_finished for h in hs):
            eng_.step()
        return [list(h.tokens) for h in hs]

    pq_base = pq_engine()
    base_greedy = pq_run(pq_base)
    base_sampled = pq_run(pq_base, sampled=True)

    _pa._INTERPRET[0] = True
    pflags.set_flags({"FLAGS_paged_kernel": "pallas"})
    try:
        kbefore = counters.snapshot()
        pk_eng = pq_engine()
        if pk_eng.stats()["kv_kernel"] != "pallas":
            violations["paged-pallas:kv_kernel"] = (
                pk_eng.stats()["kv_kernel"], "pallas")
        pk_greedy = pq_run(pk_eng)              # traces the pallas decode
        pk_sampled = pq_run(pk_eng, sampled=True)
        kwarm = counters.delta(kbefore)
        # the fused backend actually compiled, and never fell back
        if kwarm.get("kernels.paged.pallas_programs", 0) < 1:
            violations["paged-pallas:programs"] = (
                kwarm.get("kernels.paged.pallas_programs", 0), ">=1")
        if kwarm.get("kernels.paged.xla_fallbacks", 0):
            violations["paged-pallas:fallbacks"] = (
                kwarm.get("kernels.paged.xla_fallbacks", 0), 0)
        if pk_greedy != base_greedy:
            violations["paged-pallas:greedy_identity"] = (pk_greedy,
                                                          base_greedy)
        if pk_sampled != base_sampled:
            violations["paged-pallas:sampled_identity"] = (pk_sampled,
                                                           base_sampled)
        # warm steady window: every program (incl. the kernel) cached
        ksbefore = counters.snapshot()
        pq_run(pk_eng)
        ksteady = counters.delta(ksbefore)
        for k in ("serving.retraces", "jit.traces", "jit.hydrates",
                  "jit.syncs", "kernels.paged.pallas_programs",
                  "kernels.paged.xla_fallbacks"):
            if ksteady.get(k, 0):
                violations[f"paged-pallas:{k}"] = (ksteady.get(k, 0), 0)
    finally:
        pflags.set_flags({"FLAGS_paged_kernel": "off"})
        _pa._INTERPRET[0] = False

    # int8 arena twin: greedy-identical on the tiny model, ONE decode
    # program for the whole engine lifetime, zero steady retraces
    qbefore = counters.snapshot()
    pq_q = pq_engine(kv_dtype="int8")
    q_greedy = pq_run(pq_q)
    qwarm = counters.delta(qbefore)
    if q_greedy != base_greedy:
        violations["paged-quant:greedy_identity"] = (q_greedy, base_greedy)
    if qwarm.get("kernels.paged.xla_fallbacks", 0) != 1:
        violations["paged-quant:decode_programs"] = (
            qwarm.get("kernels.paged.xla_fallbacks", 0), 1)
    if not qwarm.get("serving.kv.quant.prefill_tokens", 0):
        violations["paged-quant:prefill_tokens"] = (0, ">0")
    if counters.get("serving.kv.quant.bytes_saved") <= 0:
        violations["paged-quant:bytes_saved"] = (
            counters.get("serving.kv.quant.bytes_saved"), ">0")
    qsbefore = counters.snapshot()
    pq_run(pq_q)
    qsteady = counters.delta(qsbefore)
    for k in ("serving.retraces", "jit.traces", "jit.hydrates",
              "jit.syncs", "kernels.paged.xla_fallbacks"):
        if qsteady.get(k, 0):
            violations[f"paged-quant:{k}"] = (qsteady.get(k, 0), 0)

    # the documented logit-tolerance gate, direct-call: quantized-KV
    # prefill logits and PTQ-int8 weights vs the fp32 reference
    QUANT_LOGIT_TOL = 0.05
    sw = smodel.decode_state()
    L_, nh_ = scfg.num_layers, scfg.num_heads
    hd_ = scfg.hidden_size // scfg.num_heads
    sdt = jnp.dtype(scfg.dtype)
    qids = jnp.asarray(rng.randint(0, 64, size=(1, 16)), jnp.int32)
    qbt = jnp.arange(4, dtype=jnp.int32)                # 16 tokens, bs=4
    _, _, ref_logits = smodel.prefill_paged(
        sw, qids, 0, 16, qbt,
        jnp.zeros((L_, 4, 4, nh_, hd_), sdt),
        jnp.zeros((L_, 4, 4, nh_, hd_), sdt))
    ref_l = np.asarray(ref_logits)
    quant_drift = {}
    for kvd in ("int8", "fp8"):
        adt = _pa.KV_DTYPES[kvd]
        out = smodel.prefill_paged(
            sw, qids, 0, 16, qbt,
            jnp.zeros((L_, 4, 4, nh_, hd_), adt),
            jnp.zeros((L_, 4, 4, nh_, hd_), adt),
            jnp.zeros((L_, 4, 4), jnp.float32),
            jnp.zeros((L_, 4, 4), jnp.float32))
        drift = float(np.abs(np.asarray(out[-1]) - ref_l).max())
        quant_drift[f"kv_{kvd}"] = drift
        if drift > QUANT_LOGIT_TOL * float(np.abs(ref_l).max()):
            violations[f"paged-quant:{kvd}_logits"] = (
                drift, f"<={QUANT_LOGIT_TOL}*max|ref|")
    _, _, slot_ref = smodel.prefill_slot(sw, qids, 16)
    _, _, slot_ptq = smodel.prefill_slot(ptq_int8_decode_state(smodel),
                                         qids, 16)
    ptq_drift = float(np.abs(np.asarray(slot_ptq)
                             - np.asarray(slot_ref)).max())
    quant_drift["ptq_int8"] = ptq_drift
    if ptq_drift > QUANT_LOGIT_TOL * float(
            np.abs(np.asarray(slot_ref)).max()):
        violations["paged-quant:ptq_logits"] = (
            ptq_drift, f"<={QUANT_LOGIT_TOL}*max|ref|")

    # ---- speculative gate: draft/verify fixed-shape economics -----------
    # Greedy speculative output is token-identical to the non-spec paged
    # engine for ANY draft model; a warm measured window dispatches only
    # CACHED programs — zero retraces / traces / hydrates / syncs — and
    # the engine's whole lifetime compiled exactly ONE draft decode
    # program and ONE verify program (the one-program/zero-steady-retrace
    # economics); the acceptance ledger balances exactly every round:
    # accepted + rejected == drafted, K+1 draft launches + ONE verify.
    from paddle_tpu.serving.engine import _model_programs
    from paddle_tpu.serving.kvcache import blocks_for_tokens

    paddle.seed(7)
    sdraft = GPTForCausalLM(GPTConfig(
        vocab_size=64, hidden_size=32, num_layers=1, num_heads=4,
        max_seq_len=32, use_flash_attention=False))
    sdraft.eval()
    SPEC_K = 2
    SPEC_NB = 2 * 2 * blocks_for_tokens(32, 4) + 1   # both namespaces

    def spec_engine():
        # prefix cache off so warm and measured runs chunk identically
        return LLMEngine(smodel, draft_model=sdraft, spec_k=SPEC_K,
                         kv_layout="paged", max_slots=2, max_seq_len=32,
                         min_bucket=4, block_size=4, prefill_chunk=8,
                         n_blocks=SPEC_NB, prefix_cache=False)

    sp_eng = spec_engine()
    sp_greedy = pq_run(sp_eng)    # warm: compiles draft + verify programs
    if sp_greedy != base_greedy:
        violations["spec:greedy_identity"] = (sp_greedy, base_greedy)
    spbefore = counters.snapshot()
    sp_greedy2 = pq_run(sp_eng)   # measured: every program cached
    spsteady = counters.delta(spbefore)
    if sp_greedy2 != base_greedy:
        violations["spec:greedy_identity_warm"] = (sp_greedy2, base_greedy)
    for k in ("serving.retraces", "jit.traces", "jit.hydrates",
              "jit.syncs"):
        if spsteady.get(k, 0):
            violations[f"spec:{k}"] = (spsteady.get(k, 0), 0)
    sp_drafted = spsteady.get("serving.spec.drafted", 0)
    if not sp_drafted:
        violations["spec:drafted"] = (sp_drafted, ">0")
    sp_balance = (spsteady.get("serving.spec.accepted", 0)
                  + spsteady.get("serving.spec.rejected", 0))
    if sp_balance != sp_drafted:
        violations["spec:ledger"] = (sp_balance, sp_drafted)
    sp_rounds = spsteady.get("serving.spec.verify_steps", 0)
    if (not sp_rounds or spsteady.get("serving.spec.draft_steps", 0)
            != (SPEC_K + 1) * sp_rounds):
        violations["spec:round_dispatches"] = (
            spsteady.get("serving.spec.draft_steps", 0),
            f"{SPEC_K + 1} * {sp_rounds}")
    spec_dkeys = [k for k in _model_programs(sdraft) if isinstance(k, str)
                  and k.startswith("serving.draft_paged")]
    spec_vkeys = [k for k in _model_programs(smodel) if isinstance(k, str)
                  and k.startswith("serving.verify_paged")]
    if len(spec_dkeys) != 1:
        violations["spec:draft_programs"] = (spec_dkeys, 1)
    if len(spec_vkeys) != 1:
        violations["spec:verify_programs"] = (spec_vkeys, 1)

    # ---- elastic-fleet gate: zero lost under churn, warm replicas -------
    from paddle_tpu.resilience import faultinject
    from paddle_tpu.serving import ServingFleet

    FLEET_LENS = (3, 4)   # one shared bucket {4}: one warmup compile/engine
    fleet_prompts = [rng.randint(0, 64, size=n).tolist()
                     for n in FLEET_LENS]
    frefs = []
    for p in fleet_prompts:   # single-engine reference trajectories
        h = eng.add_request(p, max_new_tokens=3)
        while not h.is_finished:
            eng.step()
        frefs.append(list(h.tokens))

    fleet = ServingFleet(smodel, replicas=2, max_slots=2, max_seq_len=32,
                         min_bucket=4, threaded=False,
                         warm_buckets=FLEET_LENS)
    # steady state: the no-fault fleet is token-identical to the single
    # engine and retraces NOTHING (every replica pre-compiled its buckets)
    flbefore = counters.snapshot()
    fhs = [fleet.submit(p, max_new_tokens=3) for p in fleet_prompts]
    fleet.join(fhs)
    flsteady = counters.delta(flbefore)
    flinvariants = {
        "serving.retraces": 0,
        "jit.traces": 0,
        "serving.fleet.dispatched": len(FLEET_LENS),
        "serving.fleet.shed": 0,
        "serving.fleet.lost": 0,
    }
    violations.update({f"fleet:{k}": (flsteady.get(k, 0), want)
                       for k, want in flinvariants.items()
                       if flsteady.get(k, 0) != want})
    for h, ref in zip(fhs, frefs):
        if list(h.tokens) != ref or h.finish_reason != "length":
            violations[f"fleet:identity@{h.rid}"] = (list(h.tokens), ref)

    # churn: kill the replica decoding the first request; it must be
    # replayed onto a survivor — zero lost, respawns == injected faults,
    # and the delivered tokens still match the single-engine reference
    chbefore = counters.snapshot()
    chs = [fleet.submit(p, max_new_tokens=3) for p in fleet_prompts]
    with faultinject.fault_schedule(f"replica_crash@{chs[0].rid}"):
        fleet.join(chs)
    fleet.drain()
    chsteady = counters.delta(chbefore)
    chinvariants = {
        "serving.fleet.lost": 0,                 # THE durability gate
        "serving.fleet.respawns": 1,             # == injected faults
        "serving.fleet.retried": 1,
        "serving.fleet.replica_deaths.crash": 1,
        "serving.fleet.replica_deaths": 1,
    }
    violations.update({f"fleet-churn:{k}": (chsteady.get(k, 0), want)
                       for k, want in chinvariants.items()
                       if chsteady.get(k, 0) != want})
    for h, ref in zip(chs, frefs):
        if list(h.tokens) != ref or h.finish_reason != "length":
            violations[f"fleet-churn:identity@{h.rid}"] = (list(h.tokens),
                                                           ref)

    # ---- disagg gate: block-granular migration economics ----------------
    # A 1 prefill + 1 decode split must (a) stay token-identical to the
    # unified paged fleet, (b) copy EXACTLY the owned non-shared blocks
    # on every hand-off — blocks_copied == sum(blocks_for_tokens(len)) -
    # blocks_shared, with a block-aligned common prefix resolved against
    # the decode replica's radix tree instead of moved again — and
    # (c) retrace nothing once the warm pass has compiled the migration
    # gather alongside the usual bucket programs.
    DIS_BS = 4
    DIS_LENS = (9, 9)
    dis_p1 = rng.randint(0, 64, size=DIS_LENS[0]).tolist()
    # same 2-block (8-token) prefix, divergent tail: the second hand-off
    # must share those 2 blocks and copy only its owned tail block
    dis_p2 = dis_p1[:8] + [(dis_p1[8] + 1) % 64]
    dis_prompts = [dis_p1, dis_p2]

    def disagg_fleet(prefill_replicas):
        return ServingFleet(smodel, replicas=2,
                            prefill_replicas=prefill_replicas,
                            max_slots=2, max_seq_len=32, min_bucket=4,
                            threaded=False, kv_layout="paged",
                            block_size=DIS_BS, n_blocks=64,
                            prefill_chunk=8, warm_buckets=DIS_LENS)

    ufleet = disagg_fleet(0)   # unified paged reference, same prompts
    drefs = []
    for p in dis_prompts:
        h = ufleet.submit(p, max_new_tokens=3)
        ufleet.join([h])
        drefs.append(list(h.tokens))
    ufleet.drain()

    dfleet = disagg_fleet(1)
    for p in dis_prompts:      # warm pass: compiles the migrate program
        dfleet.join([dfleet.submit(
            rng.randint(0, 64, size=len(p)).tolist(), max_new_tokens=3)])
    for rep in dfleet._replicas:   # measured hand-offs stay prefix-cold
        if rep.engine.prefix is not None:
            rep.engine.prefix.clear()
    dbefore = counters.snapshot()
    dhs = []
    for p in dis_prompts:      # sequential: p1 donates before p2 lands
        h = dfleet.submit(p, max_new_tokens=3)
        dfleet.join([h])
        dhs.append(h)
    dsteady = counters.delta(dbefore)
    dfleet.drain()
    owned = sum(blocks_for_tokens(len(p), DIS_BS) for p in dis_prompts)
    dinvariants = {
        "serving.retraces": 0,
        "jit.traces": 0,
        "serving.fleet.lost": 0,
        "serving.fleet.migrate.requests": len(dis_prompts),
        "serving.fleet.migrate.blocks_shared": 2,
        "serving.fleet.migrate.blocks_copied": owned - 2,
    }
    violations.update({f"disagg:{k}": (dsteady.get(k, 0), want)
                       for k, want in dinvariants.items()
                       if dsteady.get(k, 0) != want})
    for h, ref in zip(dhs, drefs):
        if list(h.tokens) != ref or h.finish_reason != "length":
            violations[f"disagg:identity@{h.rid}"] = (list(h.tokens), ref)

    # ---- tiering gate: host-RAM KV tier economics -----------------------
    # An oversubscribed paged engine (pool far smaller than the working
    # set) backed by a host tier must (a) stay token-identical — greedy
    # AND seeded sampling — to the ample-pool engine on the same
    # prompts, (b) reach an allocation-free steady state: measured
    # spill/restore churn with ZERO retraces/traces/syncs and a FLAT
    # host arena (every buffer served by the reuse pool), and (c)
    # degrade a dropped host copy (kv_spill_drop) to a deterministic
    # cache-miss replay with both tiers reconciled.
    TIER_PROMPTS = [rng.randint(0, 64, size=9).tolist() for _ in range(6)]

    def tier_run(eng_, sampled=False):
        outs = []
        for i, p in enumerate(TIER_PROMPTS):   # sequential: each finished
            h = eng_.add_request(p, max_new_tokens=4, seed=21 + i,
                                 **(pq_sample if sampled else {}))
            while not h.is_finished:           # seq donates, then the next
                eng_.step()                    # admission forces spills
            outs.append(list(h.tokens))
        return outs

    tbase = pq_engine(n_blocks=64)             # ample pool: never spills
    tier_greedy = tier_run(tbase)
    tier_sampled = tier_run(tbase, sampled=True)

    teng = pq_engine(n_blocks=8, host_kv_blocks=64)   # 7 usable blocks
    tier_run(teng)                  # warm: compiles spill/restore programs
    tier_run(teng, sampled=True)    # ...and fills the buffer reuse pool
    tbefore = counters.snapshot()
    t_greedy = tier_run(teng)
    t_sampled = tier_run(teng, sampled=True)
    tsteady = counters.delta(tbefore)
    if t_greedy != tier_greedy:
        violations["tiering:greedy_identity"] = (t_greedy, tier_greedy)
    if t_sampled != tier_sampled:
        violations["tiering:sampled_identity"] = (t_sampled, tier_sampled)
    for k in ("serving.retraces", "jit.traces", "jit.hydrates",
              "jit.syncs"):
        if tsteady.get(k, 0):
            violations[f"tiering:{k}"] = (tsteady.get(k, 0), 0)
    for k in ("serving.kv.tier.spilled_blocks",
              "serving.kv.tier.restored_blocks",
              "serving.kv.host_buf_reuse"):
        if tsteady.get(k, 0) <= 0:
            violations[f"tiering:{k}"] = (tsteady.get(k, 0), ">0")
    # the no-malloc gate: a warm tier serves every spill/restore buffer
    # from the reuse pool — the pinned arena never grows
    if tsteady.get("serving.kv.host_arena_bytes", 0):
        violations["tiering:host_arena_growth"] = (
            tsteady.get("serving.kv.host_arena_bytes", 0), 0)

    # chaos leg: re-establish the victim chain (the churn may have
    # evicted it outright), force it host-resident, then drop its host
    # copy mid-restore — admission degrades to a plain prefix miss and
    # the replayed prefill is token-identical
    th0 = teng.add_request(TIER_PROMPTS[0], max_new_tokens=4, seed=21)
    while not th0.is_finished:
        teng.step()
    with teng._cond:
        teng._spill_cold(32)
    if teng.prefix_probe(np.asarray(TIER_PROMPTS[0], np.int32))[1] <= 0:
        violations["tiering-chaos:victim_not_host"] = (
            teng.prefix_probe(np.asarray(TIER_PROMPTS[0], np.int32)), ">0")
    tdbefore = counters.snapshot()
    th = teng.add_request(TIER_PROMPTS[0], max_new_tokens=4, seed=21)
    with faultinject.fault_schedule(f"kv_spill_drop@{th.rid}"):
        while not th.is_finished:
            teng.step()
    tdrop = counters.delta(tdbefore)
    if list(th.tokens) != tier_greedy[0]:
        violations["tiering-chaos:identity"] = (list(th.tokens),
                                                tier_greedy[0])
    if tdrop.get("resilience.faults_injected.kv_spill_drop", 0) != 1:
        violations["tiering-chaos:faults"] = (
            tdrop.get("resilience.faults_injected.kv_spill_drop", 0), 1)
    if tdrop.get("serving.kv.tier.spill_drops", 0) <= 0:
        violations["tiering-chaos:spill_drops"] = (
            tdrop.get("serving.kv.tier.spill_drops", 0), ">0")
    t_live = sum(1 for b in range(1, len(teng.pool._ref))
                 if teng.pool._ref[b] > 0)
    if len(teng.pool._free) + t_live != teng.pool.capacity:
        violations["tiering-chaos:pool_leak"] = (
            len(teng.pool._free) + t_live, teng.pool.capacity)

    # ---- resilience gate 1: saves cost ONE sync each, nothing else ------
    import tempfile
    from paddle_tpu.resilience import (CheckpointManager,
                                       FaultTolerantTrainer)

    CKPT_SAVES = 2
    CKPT_STEPS_PER_SAVE = 2
    paddle.seed(0)
    cmodel = nn.Sequential(nn.Linear(16, 32), nn.GELU(), nn.Linear(32, 4))
    copt = paddle.optimizer.AdamW(1e-3, parameters=cmodel.parameters())
    cstep = pjit.CompiledTrainStep(cmodel, loss_fn, copt)
    for _ in range(WARMUP):
        cstep(x, y).numpy()
    with tempfile.TemporaryDirectory() as ckdir:
        mgr = CheckpointManager(ckdir, keep_last=2)
        cbefore = counters.snapshot()
        for i in range(CKPT_SAVES):
            for _ in range(CKPT_STEPS_PER_SAVE):
                cstep(x, y).numpy()
            mgr.save(cstep, (i + 1) * CKPT_STEPS_PER_SAVE, blocking=True)
        csteady = counters.delta(cbefore)

    ckpt_steps = CKPT_SAVES * CKPT_STEPS_PER_SAVE
    cinvariants = {
        "jit.traces": 0,
        "jit.hydrates": 0,
        "jit.cache_misses": 0,
        "jit.steps": ckpt_steps,
        "jit.host.dispatches": ckpt_steps,
        "resilience.saves": CKPT_SAVES,
        # THE budget: one counter-gated sync per save, nothing more
        "jit.syncs": CKPT_SAVES,
        "jit.host.bind_layer_state": CKPT_SAVES,
        "jit.host.bind_optimizer_state": CKPT_SAVES,
        "jit.host.layer_state": 0,
        "jit.host.optimizer_state": 0,
    }
    violations.update({f"ckpt:{k}": (csteady.get(k, 0), want)
                       for k, want in cinvariants.items()
                       if csteady.get(k, 0) != want})

    # ---- resilience gate 2: restores == injected preemptions ------------
    from paddle_tpu.io import DataLoader, TensorDataset

    FAULT_STEPS = 6
    FAULT_SCHEDULE = "preempt@3"
    INJECTED_PREEMPTIONS = 1
    paddle.seed(0)
    rmodel = nn.Sequential(nn.Linear(16, 32), nn.GELU(), nn.Linear(32, 4))
    ropt = paddle.optimizer.AdamW(1e-3, parameters=rmodel.parameters())
    rstep = pjit.CompiledTrainStep(rmodel, loss_fn, ropt)
    rx = np.random.RandomState(1)
    ds = TensorDataset(
        [paddle.to_tensor(rx.randn(FAULT_STEPS * 4, 16).astype("float32")),
         paddle.to_tensor(rx.randn(FAULT_STEPS * 4, 4).astype("float32"))])

    def loader_factory(epoch):
        return DataLoader(ds, batch_size=4, shuffle=False)

    rbefore = counters.snapshot()
    with tempfile.TemporaryDirectory() as ckdir:
        with faultinject.fault_schedule(FAULT_SCHEDULE):
            trainer = FaultTolerantTrainer(
                rstep, loader_factory, CheckpointManager(ckdir, keep_last=2),
                epochs=1, max_steps=FAULT_STEPS, save_every=3)
            rlosses = trainer.run()
    rsteady = counters.delta(rbefore)

    rinvariants = {
        "resilience.restores": INJECTED_PREEMPTIONS,
        "resilience.recoveries": INJECTED_PREEMPTIONS,
        "resilience.faults_injected.preempt": INJECTED_PREEMPTIONS,
        "resilience.corrupt_detected": 0,
        "resilience.save_failures": 0,
    }
    violations.update({f"fault:{k}": (rsteady.get(k, 0), want)
                       for k, want in rinvariants.items()
                       if rsteady.get(k, 0) != want})
    if len(rlosses) != FAULT_STEPS or not all(
            np.isfinite(v) for v in rlosses.values()):
        violations["fault:trainer_losses"] = (len(rlosses), FAULT_STEPS)

    # ---- metrics-parity gate: telemetry ON adds ZERO syncs / traces /
    # dispatches / retraces to any steady-state phase.  Fresh objects per
    # run so OFF and ON each pay the same warmup; the ON run harvests
    # (metrics_flush / prometheus_text) INSIDE the measured window — the
    # read path must be free too.
    from paddle_tpu.profiler import metrics as pmetrics

    PARITY_KEYS = ("jit.syncs", "jit.traces", "jit.host.dispatches",
                   "serving.retraces")

    def _pick(d):
        return {k: d.get(k, 0) for k in PARITY_KEYS}

    def train_phase(m):
        paddle.seed(0)
        tm = nn.Sequential(nn.Linear(16, 32), nn.GELU(), nn.Linear(32, 4))
        topt = paddle.optimizer.AdamW(1e-3, parameters=tm.parameters())
        ts = pjit.CompiledTrainStep(tm, loss_fn, topt,
                                    metrics=True if m else None)
        for _ in range(WARMUP):
            ts(x, y).numpy()
        b = counters.snapshot()
        for _ in range(MEASURE):
            ts(x, y).numpy()
        if m:
            ts.metrics_flush()
        return _pick(counters.delta(b))

    def fused_phase(m):
        paddle.seed(0)
        tm = nn.Sequential(nn.Linear(16, 32), nn.GELU(), nn.Linear(32, 4))
        topt = paddle.optimizer.AdamW(1e-3, parameters=tm.parameters())
        ts = pjit.CompiledTrainStep(tm, loss_fn, topt, fused_steps=FUSED_K,
                                    metrics=True if m else None)
        ts(window()).numpy()  # priming single-step fallback
        ts(window()).numpy()  # scan compile
        b = counters.snapshot()
        for _ in range(FUSED_MEASURE):
            ts(window()).numpy()
        if m:
            ts.metrics_flush()
        return _pick(counters.delta(b))

    def mesh_phase(m):
        from jax.sharding import Mesh as _Mesh
        mesh2 = _Mesh(np.array(jax.devices()[:2]).reshape(2), ("dp",))
        paddle.seed(0)
        tm = nn.Sequential(nn.Linear(16, 32), nn.GELU(), nn.Linear(32, 4))
        topt = paddle.optimizer.AdamW(1e-3, parameters=tm.parameters())
        ts = pjit.CompiledTrainStep(tm, loss_fn, topt, mesh=mesh2,
                                    metrics=True if m else None)
        for _ in range(WARMUP):
            ts(x, y).numpy()
        b = counters.snapshot()
        for _ in range(MEASURE):
            ts(x, y).numpy()
        if m:
            ts.metrics_flush()
        return _pick(counters.delta(b))

    def serve_phase(m):
        paddle.seed(0)
        e2 = LLMEngine(smodel, max_slots=2, max_seq_len=32, min_bucket=4)
        rng2 = np.random.RandomState(7)

        def sv(lens):
            hs = [e2.add_request(rng2.randint(0, 64, size=n).tolist(),
                                 max_new_tokens=3) for n in lens]
            while not all(h.is_finished for h in hs):
                e2.step()
                if m:   # harvesting telemetry mid-serve must be free
                    pmetrics.prometheus_text()
                    pmetrics.histogram_summaries()

        sv(SERVE_LENS_WARM)
        b = counters.snapshot()
        sv(SERVE_LENS_MEASURE)
        return _pick(counters.delta(b))

    parity_phases = [("train", train_phase), ("fused", fused_phase),
                     ("serving", serve_phase)]
    if jax.device_count() >= 2:
        parity_phases.append(("mesh", mesh_phase))
    metrics_parity = {}
    for pname, pfn in parity_phases:
        off, on = pfn(False), pfn(True)
        metrics_parity[pname] = {"off": off, "on": on}
        if on != off:
            violations[f"metrics-parity:{pname}"] = (on, off)

    # ---- trace gate: request tracing OFF is zero-overhead (no trace.*
    # movement, counter-identical parity keys vs the ON run of the same
    # fresh workload); ON, every finished engine request's stage spans
    # must account its measured TTFT + decode wall time.
    from paddle_tpu.core import flags as pflags
    from paddle_tpu.profiler import trace as rtrace

    def trace_workloads():
        """Fresh slot + paged engines + a sync fleet over identical
        deterministic workloads; returns (delta, engine handles)."""
        paddle.seed(0)
        rngt = np.random.RandomState(11)
        e3 = LLMEngine(smodel, max_slots=2, max_seq_len=32, min_bucket=4)
        p3 = LLMEngine(smodel, max_slots=2, max_seq_len=32, min_bucket=4,
                       kv_layout="paged", block_size=4, prefill_chunk=8)

        def sv(e_, lens):
            hs = [e_.add_request(rngt.randint(0, 64, size=n).tolist(),
                                 max_new_tokens=3) for n in lens]
            while not all(h.is_finished for h in hs):
                e_.step()
            return hs

        sv(e3, SERVE_LENS_WARM)
        sv(p3, SERVE_LENS_WARM)
        fl3 = ServingFleet(smodel, replicas=2, max_slots=2, max_seq_len=32,
                           min_bucket=4, threaded=False,
                           warm_buckets=SERVE_LENS_WARM)
        b = counters.snapshot()
        hs = sv(e3, SERVE_LENS_MEASURE) + sv(p3, SERVE_LENS_MEASURE)
        fhs3 = [fl3.submit(rngt.randint(0, 64, size=n).tolist(),
                           max_new_tokens=3) for n in SERVE_LENS_MEASURE]
        fl3.join(fhs3)
        d = counters.delta(b)
        fl3.drain()
        return d, hs, fhs3

    pflags.set_flags({"FLAGS_request_trace_sample": 0.0})
    toff, _, _ = trace_workloads()
    off_moved = {k: v for k, v in toff.items()
                 if k.startswith("trace.") and v}
    if off_moved:
        violations["trace-off:counters"] = (off_moved, {})
    pflags.set_flags({"FLAGS_request_trace_sample": 1.0})
    try:
        ton, ths, tfhs = trace_workloads()
    finally:
        pflags.set_flags({"FLAGS_request_trace_sample": 0.0})
    for k in PARITY_KEYS:
        if ton.get(k, 0) != toff.get(k, 0):
            violations[f"trace-parity:{k}"] = (ton.get(k, 0),
                                               toff.get(k, 0))
    # every measured request (4 engine + 2 fleet) finalized a trace
    if ton.get("trace.finished", 0) < len(ths) + len(tfhs):
        violations["trace-on:finished"] = (
            ton.get("trace.finished", 0), f">={len(ths) + len(tfhs)}")
    # span accounting: stage spans (queue + prefill + decode) sum within
    # loose tolerance of the measured arrival -> last-emit wall clock;
    # the lower bound allows the other slot's prefill to interleave, the
    # upper allows queue/kv.reserve overlap in the paged admit path
    trace_ratios = {}
    for i, h in enumerate(ths):
        lay = "slots" if i < len(SERVE_LENS_MEASURE) else "paged"
        measured = max(1, (h.last_emit_ns or h.arrival_ns) - h.arrival_ns)
        ratio = sum(h.trace.stage_ns().values()) / measured
        trace_ratios[f"{lay}:r{h.rid}"] = round(ratio, 3)
        if not 0.2 <= ratio <= 1.3:
            violations[f"trace-span-sum:{lay}:r{h.rid}"] = (round(ratio, 3),
                                                            "[0.2, 1.3]")
    rtrace.clear()

    # ---- health gate: the health plane is zero-overhead OFF (no
    # health.* movement, counter-identical parity keys vs the ON run of
    # the same fresh train + slot/paged/fleet workload), fires ZERO
    # alerts on clean ON legs, fires EXACTLY the expected alert under
    # injected chaos (slow_decode -> itl_burn, kv_pool_exhausted ->
    # kv_backpressure) with a postmortem dump naming the rule + window,
    # and the admission recommendation reaches Router.stats() plus the
    # live /alerts /slo /signals endpoints.
    import urllib.request

    from paddle_tpu.profiler import flight as pflight
    from paddle_tpu.profiler import health as phealth
    from paddle_tpu.profiler.ops import OpsServer

    def health_workloads():
        """Fresh train step + slot/paged engines (standalone monitor,
        first tick post-warm) + a sync fleet (self-ticking from pump);
        returns the measured counter delta."""
        paddle.seed(0)
        rngh = np.random.RandomState(13)
        hm = nn.Sequential(nn.Linear(16, 32), nn.GELU(), nn.Linear(32, 4))
        hopt = paddle.optimizer.AdamW(1e-3, parameters=hm.parameters())
        hstep = pjit.CompiledTrainStep(hm, loss_fn, hopt)
        e5 = LLMEngine(smodel, max_slots=2, max_seq_len=32, min_bucket=4)
        p5 = LLMEngine(smodel, max_slots=2, max_seq_len=32, min_bucket=4,
                       kv_layout="paged", block_size=4, prefill_chunk=8)
        mon = phealth.HealthMonitor(interval_s=0.0).attach(e5).attach(p5)

        def sv(e_, lens, tick=False):
            hs = [e_.add_request(rngh.randint(0, 64, size=n).tolist(),
                                 max_new_tokens=3) for n in lens]
            while not all(h.is_finished for h in hs):
                e_.step()
                if tick:    # live monitoring mid-serve must be free
                    mon.maybe_tick()
            return hs

        for _ in range(WARMUP):
            hstep(x, y).numpy()
        sv(e5, SERVE_LENS_WARM)
        sv(p5, SERVE_LENS_WARM)
        fl5 = ServingFleet(smodel, replicas=2, max_slots=2, max_seq_len=32,
                           min_bucket=4, threaded=False,
                           warm_buckets=SERVE_LENS_WARM)
        b = counters.snapshot()
        for _ in range(MEASURE):
            hstep(x, y).numpy()
        mon.maybe_tick()
        sv(e5, SERVE_LENS_MEASURE, tick=True)
        sv(p5, SERVE_LENS_MEASURE, tick=True)
        fhs = [fl5.submit(rngh.randint(0, 64, size=n).tolist(),
                          max_new_tokens=3) for n in SERVE_LENS_MEASURE]
        fl5.join(fhs)
        d = counters.delta(b)
        fl5.drain()
        return d

    pflags.set_flags({"FLAGS_health": False})
    hoff = health_workloads()
    hoff_moved = {k: v for k, v in hoff.items()
                  if k.startswith("health.") and v}
    if hoff_moved:
        violations["health-off:counters"] = (hoff_moved, {})
    pflags.set_flags({"FLAGS_health": True, "FLAGS_health_interval_s": 0.0})
    try:
        hon = health_workloads()
        for k in PARITY_KEYS:
            if hon.get(k, 0) != hoff.get(k, 0):
                violations[f"health-parity:{k}"] = (hon.get(k, 0),
                                                    hoff.get(k, 0))
        hclean_fired = {k: v for k, v in hon.items()
                        if k.startswith("health.alerts.fired") and v}
        if hclean_fired:
            violations["health-clean:alerts"] = (hclean_fired, {})
        if not hon.get("health.ticks"):
            violations["health-on:ticks"] = (hon.get("health.ticks", 0),
                                             ">=1")

        # chaos leg 1: a stalled decode loop must trip the fast+slow ITL
        # burn windows of the fleet's own monitor — and nothing else
        rngh6 = np.random.RandomState(17)
        fl6 = ServingFleet(smodel, replicas=2, max_slots=2, max_seq_len=32,
                           min_bucket=4, threaded=False,
                           warm_buckets=SERVE_LENS_WARM,
                           heartbeat_timeout_s=30.0)

        def settle6(deadline_s=15.0):
            """Tick until nothing is firing — a loaded CI box can push
            nominal ITL over the CPU-scale burn target; once traffic
            stops the windows drain and spurious alerts resolve.  The
            router refuses shed=True admissions while critical, so the
            next leg must not start until the plane is quiet."""
            t0 = time.monotonic()
            while time.monotonic() - t0 < deadline_s:
                fl6.health.maybe_tick()
                if not fl6.health.firing():
                    return True
                time.sleep(0.05)
            return False

        # clean leg on the same fleet: silence.  Retried (re-baselined)
        # on a box hiccup so the chaos expectation below stays exact.
        for _ in range(3):
            b = counters.snapshot()
            chs6 = [fl6.submit(rngh6.randint(0, 64, size=3).tolist(),
                               max_new_tokens=6) for _ in range(4)]
            fl6.join(chs6)
            hclean6 = {k: v for k, v in counters.delta(b).items()
                       if k.startswith("health.alerts.fired.") and v}
            if not hclean6:
                break
            settle6()
        if hclean6:
            violations["health-chaos:clean-leg"] = (hclean6, {})
        chs6 = [fl6.submit(rngh6.randint(0, 64, size=3).tolist(),
                           max_new_tokens=8) for _ in range(4)]
        with faultinject.fault_schedule(f"slow_decode@{chs6[0].rid}*8"):
            fl6.join(chs6)
        hfired = {k: v for k, v in counters.delta(b).items()
                  if k.startswith("health.alerts.fired.")}
        if hfired != {"health.alerts.fired.itl_burn": 1}:
            violations["health-chaos:slow_decode"] = (
                hfired, {"health.alerts.fired.itl_burn": 1})
        hb = pflight.load(pflight.last_dump_path())
        hdump = (hb.get("reason"),
                 (hb.get("context") or {}).get("rule"),
                 bool(((hb.get("context") or {}).get("window") or {})
                      .get("seconds")))
        if hdump != ("health_itl_burn", "itl_burn", True):
            violations["health-chaos:slow_decode-dump"] = (
                hdump, ("health_itl_burn", "itl_burn", True))
        # the recommendation must reach the router and the live ops
        # endpoints while the alert is still firing
        hadm = fl6.router.stats()["health"]["admission_level"]
        if hadm != "critical":
            violations["health-chaos:admission"] = (hadm, "critical")
        ops_live = {}
        with OpsServer(fleet=fl6) as srv:
            for ep in ("/alerts", "/slo", "/signals", "/healthz"):
                body = json.loads(urllib.request.urlopen(
                    srv.url(ep), timeout=10).read())
                ops_live[ep] = sorted(body)[:4]
                if ep == "/alerts" and body.get("firing") != ["itl_burn"]:
                    violations["health-ops:alerts"] = (body.get("firing"),
                                                       ["itl_burn"])
                if ep == "/healthz" and body.get("status") != "degraded":
                    violations["health-ops:healthz"] = (body.get("status"),
                                                        "degraded")
        fl6.drain()

        # chaos leg 2: refused block reservations must trip the KV
        # backpressure watchdog on a standalone paged engine (first tick
        # after warmup so compile activity stays outside every window)
        p6 = LLMEngine(smodel, max_slots=2, max_seq_len=32, min_bucket=4,
                       kv_layout="paged", block_size=4, prefill_chunk=8)
        mon6 = phealth.HealthMonitor(
            rules=[wd for wd in phealth.default_watchdogs()
                   if wd.name in ("kv_backpressure", "kv_conservation")],
            interval_s=0.0).attach(p6)
        h0 = p6.add_request(rngh6.randint(0, 64, size=6).tolist(),
                            max_new_tokens=3)
        while not h0.is_finished:
            p6.step()
        mon6.maybe_tick()
        b = counters.snapshot()
        h1 = p6.add_request(rngh6.randint(0, 64, size=6).tolist(),
                            max_new_tokens=3)
        with faultinject.fault_schedule(f"kv_pool_exhausted@{h1.rid}"):
            for _ in range(300):
                p6.step()
                mon6.maybe_tick()
                if h1.is_finished:
                    break
        kfired = {k: v for k, v in counters.delta(b).items()
                  if k.startswith("health.alerts.fired.")}
        if kfired != {"health.alerts.fired.kv_backpressure": 1}:
            violations["health-chaos:kv_pool_exhausted"] = (
                kfired, {"health.alerts.fired.kv_backpressure": 1})
        kb = pflight.load(pflight.last_dump_path())
        kwin = (kb.get("context") or {}).get("window") or {}
        kdump = (kb.get("reason"),
                 (kwin.get("delta") or {}).get("serving.kv.pool_exhausted",
                                               0) >= 1)
        if kdump != ("health_kv_backpressure", True):
            violations["health-chaos:kv-dump"] = (
                kdump, ("health_kv_backpressure", True))
    finally:
        pflags.set_flags({"FLAGS_health": False,
                          "FLAGS_health_interval_s": 1.0})

    # ---- program-audit gate: FLAGS_program_audit=enforce holds over the
    # whole compiled-program surface (train single/fused/mesh-dp2 +
    # slot/paged serving incl. the COW copy program) with zero findings,
    # and audit ON adds ZERO syncs/traces/dispatches/retraces to any
    # measured steady-state window — audits run once per program, at the
    # compile/warmup sites.  Then each deliberately-broken fixture must be
    # caught and named by rule.
    from paddle_tpu import analysis as panalysis

    def audit_workloads():
        """Fresh train steps (metrics / fused / mesh-dp2) + slot/paged
        engines over fixed workloads.  All compiles (and audits, when on)
        happen before the snapshot; returns the measured parity delta."""
        panalysis.reset_audited()
        paddle.seed(0)
        am = nn.Sequential(nn.Linear(16, 32), nn.GELU(), nn.Linear(32, 4))
        aopt = paddle.optimizer.AdamW(1e-3, parameters=am.parameters())
        astep = pjit.CompiledTrainStep(am, loss_fn, aopt, metrics=True)
        for _ in range(WARMUP):
            astep(x, y).numpy()
        paddle.seed(0)
        afm = nn.Sequential(nn.Linear(16, 32), nn.GELU(), nn.Linear(32, 4))
        afopt = paddle.optimizer.AdamW(1e-3, parameters=afm.parameters())
        afstep = pjit.CompiledTrainStep(afm, loss_fn, afopt,
                                        fused_steps=FUSED_K)
        afstep(window()).numpy()  # priming single-step fallback
        afstep(window()).numpy()  # scan compile
        amstep = None
        if jax.device_count() >= 2:
            from jax.sharding import Mesh as _Mesh
            # the mesh step program shares its name with the single-device
            # one — re-arm the once-per-name audit so it is audited too
            panalysis.reset_audited()
            amesh = _Mesh(np.array(jax.devices()[:2]).reshape(2), ("dp",))
            paddle.seed(0)
            amm = nn.Sequential(nn.Linear(16, 32), nn.GELU(),
                                nn.Linear(32, 4))
            amopt = paddle.optimizer.AdamW(1e-3,
                                           parameters=amm.parameters())
            amstep = pjit.CompiledTrainStep(amm, loss_fn, amopt, mesh=amesh)
            for _ in range(WARMUP):
                amstep(x, y).numpy()
        e4 = LLMEngine(smodel, max_slots=2, max_seq_len=32, min_bucket=4)
        p4 = LLMEngine(smodel, max_slots=2, max_seq_len=32, min_bucket=4,
                       kv_layout="paged", block_size=4, prefill_chunk=8)
        rng4 = np.random.RandomState(7)

        def sv(e_, lens):
            hs = [e_.add_request(rng4.randint(0, 64, size=n).tolist(),
                                 max_new_tokens=3) for n in lens]
            while not all(h.is_finished for h in hs):
                e_.step()
            return hs

        sv(e4, SERVE_LENS_WARM)
        ah0 = sv(p4, SERVE_LENS_WARM)[0]
        # compile (and audit) the COW copy program at warmup: extend a
        # cached sequence past its partial prefix block
        acw = (list(ah0.prompt) + ah0.tokens)[:5] + [int(ah0.prompt[0])]
        ahc = p4.add_request(acw, max_new_tokens=3)
        while not ahc.is_finished:
            p4.step()
        # speculative engine: audits the draft-prefill chunk, draft
        # decode and verify programs at their compile/warmup sites
        sp4 = LLMEngine(smodel, draft_model=sdraft, spec_k=SPEC_K,
                        kv_layout="paged", max_slots=2, max_seq_len=32,
                        min_bucket=4, block_size=4, prefill_chunk=8,
                        n_blocks=SPEC_NB, prefix_cache=False)
        sv(sp4, SERVE_LENS_WARM)

        b = counters.snapshot()
        for _ in range(MEASURE):
            astep(x, y).numpy()
        for _ in range(FUSED_MEASURE):
            afstep(window()).numpy()
        if amstep is not None:
            for _ in range(MEASURE):
                amstep(x, y).numpy()
        sv(e4, SERVE_LENS_MEASURE)
        sv(p4, SERVE_LENS_MEASURE)
        sv(sp4, SERVE_LENS_MEASURE)
        return _pick(counters.delta(b))

    pflags.set_flags({"FLAGS_program_audit": "off"})
    audit_off = audit_workloads()
    pflags.set_flags({"FLAGS_program_audit": "enforce"})
    abefore = counters.snapshot()
    try:
        # any finding raises ProgramAuditError straight out of run()
        audit_on = audit_workloads()
    finally:
        pflags.set_flags({"FLAGS_program_audit": "off"})
    audit_delta = counters.delta(abefore)
    if audit_on != audit_off:
        violations["audit-parity"] = (audit_on, audit_off)
    audits_run = audit_delta.get("analysis.audits", 0)
    if audits_run < 10:   # step x2 + window + mesh step + 5 slot + 3+ paged
        violations["audit:coverage"] = (audits_run, ">=10")
    if audit_delta.get("analysis.findings", 0):
        violations["audit:findings"] = (
            audit_delta.get("analysis.findings", 0), 0)

    # seeded-broken fixtures: the auditor must catch each one by name
    import jax.numpy as jnp

    def cb_prog(v):
        out = jax.pure_callback(
            lambda a: np.asarray(a), jax.ShapeDtypeStruct(v.shape, v.dtype),
            v)
        return out + 1

    def drop_prog(v):   # donated (4,4) input, only a scalar output
        return jnp.sum(v)

    fixture_got = {}
    v4 = jnp.ones((4, 4), jnp.float32)
    rep = panalysis.audit_program("fixture.callback", jax.jit(cb_prog), v4)
    fixture_got["host-callback"] = sorted({f.rule for f in rep.findings})
    rep = panalysis.audit_program(
        "fixture.donation", jax.jit(drop_prog, donate_argnums=(0,)), v4,
        donate_argnums=(0,))
    fixture_got["donation-dropped"] = sorted({f.rule for f in rep.findings})
    from jax import export as jexport
    bdim = jexport.symbolic_shape("b, 4")
    rep = panalysis.audit_program(
        "fixture.dynamic", jax.jit(lambda z: z * 2),
        jax.ShapeDtypeStruct(bdim, jnp.float32), compile_program=False)
    fixture_got["dynamic-shape"] = sorted({f.rule for f in rep.findings})
    for want_rule, got_rules in fixture_got.items():
        if want_rule not in got_rules:
            violations[f"audit-fixture:{want_rule}"] = (got_rules,
                                                        want_rule)

    # ---- devicetime gate: the device-time ledger is zero-overhead OFF
    # (sample=0 moves NO jit.devicetime.* / program.* state and the run
    # is counter-identical on the parity keys vs the ON run of the same
    # fresh slot/paged/spec workload); ON (sample=4) pays EXACTLY the
    # budgeted fences — sampled_syncs == ceil(dispatches / 4) over a
    # window anchored by devicetime.reset() — with token identity, zero
    # retraces, and a populated ledger whose MFU/roofline gauges survive
    # GET /programs and a bench_compare --attribute run that names the
    # dominant program.
    import contextlib
    import importlib.util
    import io as _io
    import tempfile

    from paddle_tpu.profiler import devicetime as pdt

    def dt_workloads():
        """Fresh slot + paged + spec engines over the pq workload; warm
        first so every compile (and, under sampling, its first noted
        dispatches) stays outside the measured window, which is anchored
        by an explicit ledger reset."""
        paddle.seed(0)
        e7 = LLMEngine(smodel, max_slots=2, max_seq_len=32, min_bucket=4)
        p7 = pq_engine()
        s7 = spec_engine()
        for eng7 in (e7, p7, s7):
            pq_run(eng7)                      # warm: compiles cached
        pdt.reset()                           # anchor the sample window
        b = counters.snapshot()
        outs = [pq_run(eng7) for eng7 in (e7, p7, s7)]
        return counters.delta(b), outs

    dt_off, dt_off_tokens = dt_workloads()
    dt_off_moved = {k: v for k, v in dt_off.items()
                    if k.startswith(("jit.devicetime.", "program.")) and v}
    if dt_off_moved:
        violations["devicetime-off:counters"] = (dt_off_moved, {})
    if dt_off_tokens[1:] != [base_greedy, base_greedy]:
        violations["devicetime-off:identity"] = (dt_off_tokens[1:],
                                                 base_greedy)

    # AOT-capture FLOPs/HBM bytes for every program name once (telemetry
    # pass), then sample with telemetry back OFF but peaks kept so the
    # ledger's efficiency join has both sides to work with.
    dt_saved = {k: pflags.flag(k) for k in
                ("FLAGS_peak_tflops", "FLAGS_peak_hbm_gbps",
                 "FLAGS_device_telemetry", "FLAGS_device_time_sample")}
    pflags.set_flags({"FLAGS_device_telemetry": True,
                      "FLAGS_peak_tflops": 197.0,
                      "FLAGS_peak_hbm_gbps": 819.0})
    try:
        dt_workloads()
        pflags.set_flags({"FLAGS_device_telemetry": False,
                          "FLAGS_device_time_sample": 4})
        dt_on, dt_on_tokens = dt_workloads()
    finally:
        # telemetry + sampling restored here; the PEAK flags stay live
        # through the reads below (the efficiency join reads them at
        # snapshot time) and are restored at the end of the phase
        pflags.set_flags({
            "FLAGS_device_telemetry": dt_saved["FLAGS_device_telemetry"],
            "FLAGS_device_time_sample":
                dt_saved["FLAGS_device_time_sample"]})
    for k in PARITY_KEYS:
        if dt_on.get(k, 0) != dt_off.get(k, 0):
            violations[f"devicetime-parity:{k}"] = (dt_on.get(k, 0),
                                                    dt_off.get(k, 0))
    if dt_on_tokens != dt_off_tokens:
        violations["devicetime-on:identity"] = (dt_on_tokens,
                                                dt_off_tokens)
    dt_disp = dt_on.get("jit.devicetime.dispatches", 0)
    dt_syncs = dt_on.get("jit.devicetime.sampled_syncs", 0)
    if not dt_disp:
        violations["devicetime-on:dispatches"] = (dt_disp, ">0")
    if dt_syncs != -(-dt_disp // 4):
        violations["devicetime-on:sync_budget"] = (
            dt_syncs, f"ceil({dt_disp}/4)")

    # the ledger the measured ON window left behind (the flag observer
    # never resets it): rows present, at least one with a joined MFU
    dt_snap = pdt.snapshot()
    if not dt_snap["programs"]:
        violations["devicetime:ledger"] = (0, ">=1 program row")
    dt_mfu_rows = [p["name"] for p in dt_snap["programs"]
                   if p.get("mfu") is not None]
    if not dt_mfu_rows:
        violations["devicetime:mfu_rows"] = ([], ">=1 row with MFU")

    # the same table over the wire
    with OpsServer() as dsrv:
        with urllib.request.urlopen(dsrv.url("/programs"),
                                    timeout=10) as r:
            dt_http = json.loads(r.read())
    if len(dt_http.get("programs") or []) != len(dt_snap["programs"]):
        violations["devicetime:/programs"] = (
            len(dt_http.get("programs") or []), len(dt_snap["programs"]))
    if not [p for p in dt_http.get("programs") or []
            if p.get("mfu") is not None]:
        violations["devicetime:/programs-mfu"] = ([], ">=1 row with MFU")

    # per-program regression attribution: a synthetic candidate run that
    # regresses throughput while the dominant program's device-time
    # share grows must be attributed to that program by name
    dt_block = pdt.bench_block(top=8)
    dt_dominant = max(dt_block["programs"],
                      key=lambda n: dt_block["programs"][n].get("share")
                      or 0.0)
    bc_spec = importlib.util.spec_from_file_location(
        "bench_compare", os.path.join(os.path.dirname(__file__),
                                      "bench_compare.py"))
    bc_mod = importlib.util.module_from_spec(bc_spec)
    bc_spec.loader.exec_module(bc_mod)
    with tempfile.TemporaryDirectory() as td:
        cand_block = json.loads(json.dumps(dt_block))
        crow = cand_block["programs"][dt_dominant]
        crow["share"] = min(1.0, (crow.get("share") or 0.5) + 0.2)
        for i, legs in ((1, {"paged": {"tokens_per_sec": 100.0,
                                       "devicetime": dt_block}}),
                        (2, {"paged": {"tokens_per_sec": 70.0,
                                       "devicetime": cand_block}})):
            with open(os.path.join(td, f"BENCH_r{i:02d}.json"), "w") as f:
                json.dump({"rc": 0, "parsed": {"legs": legs}}, f)
        buf = _io.StringIO()
        with contextlib.redirect_stdout(buf):
            bc_mod.main(["--glob", os.path.join(td, "BENCH_r0*.json"),
                         "--attribute"])
        dt_attr_out = buf.getvalue()
    if dt_dominant not in dt_attr_out:
        violations["devicetime:attribution"] = (
            dt_attr_out.splitlines()[-6:], dt_dominant)
    pflags.set_flags({"FLAGS_peak_tflops": dt_saved["FLAGS_peak_tflops"],
                      "FLAGS_peak_hbm_gbps":
                          dt_saved["FLAGS_peak_hbm_gbps"]})
    pdt.reset()

    # ---- mesh-serving gate: tensor-parallel paged decode over the
    # StateArena.  An mp2 engine must be token-identical to the
    # single-device engine (greedy AND seeded), hold the zero-steady-
    # retrace/hydrate/sync economics with dispatch counts unchanged,
    # carry the KV pool genuinely head-sharded per chip, and prove —
    # via the auditor's compiled-HLO census under enforce — that every
    # cross-chip reduction is an in-graph collective (the host never
    # launches one).
    import warnings as _warnings

    from jax.sharding import Mesh as _SMesh
    from paddle_tpu.serving.arena import StateArena  # noqa: F401 (import gate)

    if jax.device_count() >= 2:
        ms_mesh = _SMesh(np.array(jax.devices()[:2]).reshape(2), ("mp",))

        # unsharded dispatch-count reference over a warm steady window
        ms_ref_eng = pq_engine()
        pq_run(ms_ref_eng)                       # warm
        ms_ref_before = counters.snapshot()
        pq_run(ms_ref_eng)
        ms_ref = counters.delta(ms_ref_before)

        ms_eng = pq_engine(mesh=ms_mesh)
        ms_greedy = pq_run(ms_eng)               # traces the [mp2] programs
        ms_sampled = pq_run(ms_eng, sampled=True)
        if ms_greedy != base_greedy:
            violations["meshserve:greedy_identity"] = (ms_greedy, base_greedy)
        if ms_sampled != base_sampled:
            violations["meshserve:sampled_identity"] = (ms_sampled,
                                                        base_sampled)
        if counters.get("serving.mesh.spec_degraded"):
            violations["meshserve:spec_degraded"] = (
                counters.get("serving.mesh.spec_degraded"), 0)
        # sharded-shard-shape proof on the KV pool: nh/mp heads per chip
        ms_shard = ms_eng.arena.shard_shape("pool_k")
        ms_want = (scfg.num_layers, ms_eng.n_blocks, 4,
                   scfg.num_heads // 2,
                   scfg.hidden_size // scfg.num_heads)
        if ms_shard != ms_want:
            violations["meshserve:kv_shard_shape"] = (ms_shard, ms_want)
        # warm steady window: zero retraces/hydrates/syncs, no arena
        # misses or rebuilds, zero host-launched collectives
        ms_before = counters.snapshot()
        pq_run(ms_eng)
        mssteady = counters.delta(ms_before)
        for k in ("serving.retraces", "jit.traces", "jit.hydrates",
                  "jit.syncs", "serving.arena.program_misses",
                  "serving.arena.program_rebuilds",
                  "dist.collective_launches"):
            if mssteady.get(k, 0):
                violations[f"meshserve:{k}"] = (mssteady.get(k, 0), 0)
        # dispatch economics unchanged vs the unsharded twin
        for k in ("serving.decode_steps", "serving.kv.prefill_chunks",
                  "serving.prefill_batches"):
            if mssteady.get(k, 0) != ms_ref.get(k, 0):
                violations[f"meshserve:dispatch:{k}"] = (mssteady.get(k, 0),
                                                         ms_ref.get(k, 0))
        # in-graph-collectives-only proof: a fresh mesh engine under
        # enforce must audit clean, with the allowlisted census > 0
        from paddle_tpu.analysis import program_audit as _msaudit
        _msaudit.reset_audited()
        pflags.set_flags({"FLAGS_program_audit": "enforce"})
        try:
            msa_before = counters.snapshot()
            with _warnings.catch_warnings():
                _warnings.simplefilter("ignore")
                msa_eng = pq_engine(mesh=ms_mesh)
                msa_tokens = pq_run(msa_eng)
            msa_delta = counters.delta(msa_before)
        finally:
            pflags.set_flags({"FLAGS_program_audit": "off"})
            _msaudit.reset_audited()
        if msa_tokens != base_greedy:
            violations["meshserve:audited_identity"] = (msa_tokens,
                                                        base_greedy)
        if msa_delta.get("analysis.collectives_in_graph", 0) < 1:
            violations["meshserve:collectives_in_graph"] = (
                msa_delta.get("analysis.collectives_in_graph", 0), ">=1")
        if msa_delta.get("analysis.findings", 0):
            violations["meshserve:audit_findings"] = (
                msa_delta.get("analysis.findings", 0), 0)
        mssteady["analysis.collectives_in_graph"] = msa_delta.get(
            "analysis.collectives_in_graph", 0)
    else:
        mssteady = {"skipped":
                    f"needs 2 devices, have {jax.device_count()}"}

    # ---- adapters gate: multi-tenant LoRA serving.  Adapter ids are
    # OPERANDS, so one compiled program serves any tenant mix: the
    # heterogeneous batch below (base + three tenants in the same decode
    # step) must match per-tenant sequential runs token for token, hold
    # the zero-retrace steady economics with dispatch counts equal to
    # the adapter-free twin, and survive an eviction-then-reuse cycle
    # with loads moving but programs never retracing.
    from paddle_tpu.serving.adapters import random_lora_factors as _alf

    ad_tenants = ("acme", "bravo", "coyote")
    ad_factors = {t: _alf(scfg, 3, seed=10 + i, scale=1.0)
                  for i, t in enumerate(ad_tenants)}
    ad_prompts = [rng.randint(0, 64, size=n).tolist()
                  for n in (5, 9, 5, 9)]
    ad_mix = (None, "acme", "bravo", "coyote")

    def ad_engine(slots=5, **kw):
        if slots:
            kw.update(adapter_slots=slots, adapter_rank=4)
        return LLMEngine(smodel, max_slots=4, max_seq_len=32,
                         min_bucket=4, kv_layout="paged", block_size=4,
                         prefill_chunk=8, **kw)

    def ad_run(eng_, mix=ad_mix):
        hs = [eng_.add_request(p, max_new_tokens=3, seed=21 + i,
                               adapter=t)
              for i, (p, t) in enumerate(zip(ad_prompts, mix))]
        while not all(h.is_finished for h in hs):
            eng_.step()
        return [list(h.tokens) for h in hs]

    # adapter-free twin over the identical workload: the dispatch
    # economics reference AND the per-row base tokens
    ad_ref_eng = ad_engine(slots=0)
    ad_ref_tokens = ad_run(ad_ref_eng, mix=(None,) * 4)     # warm
    ad_ref_before = counters.snapshot()
    if ad_run(ad_ref_eng, mix=(None,) * 4) != ad_ref_tokens:
        violations["adapters:ref_determinism"] = ("drift", ad_ref_tokens)
    ad_ref = counters.delta(ad_ref_before)

    ad_eng = ad_engine()
    for t in ad_tenants:
        ad_eng.register_adapter(t, ad_factors[t])
    ad_mixed = ad_run(ad_eng)      # warm: traces +lora programs, cold loads
    # base row bitwise passthrough; every tenant row diverges from base
    if ad_mixed[0] != ad_ref_tokens[0]:
        violations["adapters:base_passthrough"] = (ad_mixed[0],
                                                   ad_ref_tokens[0])
    for i, t in enumerate(ad_mix[1:], start=1):
        if ad_mixed[i] == ad_ref_tokens[i]:
            violations[f"adapters:inert:{t}"] = (ad_mixed[i],
                                                 "!= base tokens")
    # base-ONLY traffic through the adapter engine: adapter-free twin
    # row for row (slot 0 selects the un-adapted activations themselves)
    if ad_run(ad_eng, mix=(None,) * 4) != ad_ref_tokens:
        violations["adapters:base_only_identity"] = ("drift",
                                                     ad_ref_tokens)
    # heterogeneous batch == per-tenant sequential on a fresh engine
    ad_seq_eng = ad_engine()
    for t in ad_tenants:
        ad_seq_eng.register_adapter(t, ad_factors[t])
    for i, t in enumerate(ad_mix[1:], start=1):
        h_ = ad_seq_eng.add_request(ad_prompts[i], max_new_tokens=3,
                                    seed=21 + i, adapter=t)
        while not h_.is_finished:
            ad_seq_eng.step()
        if list(h_.tokens) != ad_mixed[i]:
            violations[f"adapters:sequential:{t}"] = (list(h_.tokens),
                                                      ad_mixed[i])
    # warm steady window: ONE program economy — zero retraces/hydrates/
    # syncs/arena misses, zero adapter loads (all tenants resident),
    # dispatch counts equal to the adapter-free twin
    ad_before = counters.snapshot()
    if ad_run(ad_eng) != ad_mixed:
        violations["adapters:determinism"] = ("drift", ad_mixed)
    adsteady = counters.delta(ad_before)
    for k in ("serving.retraces", "jit.traces", "jit.hydrates",
              "jit.syncs", "serving.arena.program_misses",
              "serving.arena.program_rebuilds", "serving.adapter.loads",
              "serving.adapter.evictions"):
        if adsteady.get(k, 0):
            violations[f"adapters:{k}"] = (adsteady.get(k, 0), 0)
    for k in ("serving.decode_steps", "serving.kv.prefill_chunks",
              "serving.prefill_batches"):
        if adsteady.get(k, 0) != ad_ref.get(k, 0):
            violations[f"adapters:dispatch:{k}"] = (adsteady.get(k, 0),
                                                    ad_ref.get(k, 0))
    # eviction-then-reuse: three MORE tenants through the 5-slot arena
    # force at least one LRU eviction; reloading the original mix pages
    # the evicted tenant back in — loads move, programs never retrace,
    # tokens never change
    for j, t in enumerate(("dingo", "echo", "foxtrot")):
        ad_eng.register_adapter(t, _alf(scfg, 3, seed=40 + j, scale=1.0))
    ad_run(ad_eng, mix=(None, "dingo", "echo", "foxtrot"))
    ad_stats = ad_eng.stats()["adapters"]
    if ad_stats["evictions"] < 1:
        violations["adapters:evictions"] = (ad_stats["evictions"], ">=1")
    ad_re_before = counters.snapshot()
    if ad_run(ad_eng) != ad_mixed:
        violations["adapters:reuse_identity"] = ("drift", ad_mixed)
    ad_reuse = counters.delta(ad_re_before)
    if ad_reuse.get("serving.adapter.loads", 0) < 1:
        violations["adapters:reuse_loads"] = (
            ad_reuse.get("serving.adapter.loads", 0), ">=1")
    for k in ("serving.retraces", "jit.traces"):
        if ad_reuse.get(k, 0):
            violations[f"adapters:reuse:{k}"] = (ad_reuse.get(k, 0), 0)

    result = {"metric": "steady_state_counter_violations",
              "value": len(violations),
              "unit": f"violations/{MEASURE} steps "
                      f"+ {FUSED_MEASURE} fused windows "
                      f"+ {len(SERVE_LENS_MEASURE)} served requests",
              "violations": {k: {"got": got, "want": want}
                             for k, (got, want) in violations.items()},
              "steady_delta": steady,
              "fused_steady_delta": fsteady,
              "mesh_steady_delta": msteady,
              "mesh_fused_delta": fmsteady,
              "serving_steady_delta": ssteady,
              "serving_prefill_programs": eng.stats()["prefill_programs"],
              "paged_steady_delta": psteady,
              "paged_prefill_programs": peng.stats()["prefill_programs"],
              "paged_prefix": {"hits": pc_hits,
                               "chunks_cached": pc_chunks,
                               "chunks_nocache": nc_chunks},
              "paged_pallas_steady_delta": ksteady,
              "paged_quant_steady_delta": qsteady,
              "paged_quant_logit_drift": quant_drift,
              "spec_steady_delta": {k: v for k, v in spsteady.items()
                                    if k.startswith(("serving.spec.",
                                                     "serving.retraces",
                                                     "jit."))},
              "spec_programs": {"draft": spec_dkeys,
                                "verify": spec_vkeys},
              "fleet_steady_delta": flsteady,
              "fleet_churn_delta": {k: v for k, v in chsteady.items()
                                    if k.startswith("serving.fleet.")},
              "disagg_delta": {k: v for k, v in dsteady.items()
                               if k.startswith(("serving.fleet.migrate.",
                                                "serving.retraces"))},
              "tiering_delta": {k: v for k, v in tsteady.items()
                                if k.startswith(("serving.kv.tier.",
                                                 "serving.kv.host_",
                                                 "serving.retraces",
                                                 "jit.traces"))},
              "tiering_chaos": {k: v for k, v in tdrop.items()
                                if k.startswith(
                                    ("serving.kv.tier.",
                                     "resilience.faults_injected"))},
              "ckpt_steady_delta": {k: v for k, v in csteady.items()
                                    if k.startswith(("jit.", "resilience."))},
              "fault_delta": {k: v for k, v in rsteady.items()
                              if k.startswith("resilience.")},
              "metrics_parity": metrics_parity,
              "trace_parity": {"off": _pick(toff), "on": _pick(ton),
                               "off_trace_moved": off_moved,
                               "on_finished": ton.get("trace.finished", 0)},
              "trace_span_ratios": trace_ratios,
              "health_parity": {"off": _pick(hoff), "on": _pick(hon),
                                "off_health_moved": hoff_moved,
                                "on_ticks": hon.get("health.ticks", 0),
                                "clean_fired": hclean_fired},
              "health_chaos": {"slow_decode_fired": hfired,
                               "slow_decode_dump": list(hdump),
                               "kv_fired": kfired,
                               "kv_dump": list(kdump),
                               "admission_level": hadm,
                               "ops": ops_live},
              "program_audit": {"off": audit_off, "on": audit_on,
                                "audits": audits_run,
                                "findings": audit_delta.get(
                                    "analysis.findings", 0),
                                "fixtures": fixture_got},
              "meshserve_delta": {k: v for k, v in mssteady.items()
                                  if not k.endswith("_ns")},
              "adapters_delta": {
                  "steady": {k: v for k, v in adsteady.items()
                             if k.startswith(("serving.adapter.",
                                              "serving.retraces",
                                              "jit.traces"))},
                  "reuse": {k: v for k, v in ad_reuse.items()
                            if k.startswith(("serving.adapter.",
                                             "serving.retraces",
                                             "jit.traces"))},
                  "evictions": ad_stats["evictions"],
                  "resident": ad_stats["resident"]},
              "devicetime": {"off": _pick(dt_off), "on": _pick(dt_on),
                             "off_moved": dt_off_moved,
                             "dispatches": dt_disp,
                             "sampled_syncs": dt_syncs,
                             "ledger_programs": len(dt_snap["programs"]),
                             "mfu_rows": dt_mfu_rows[:4],
                             "attribution_dominant": dt_dominant}}
    print(json.dumps(result))
    if violations:
        raise AssertionError(
            "steady-state counter invariants violated (got != want): "
            + ", ".join(f"{k}: {got} != {want}"
                        for k, (got, want) in sorted(violations.items())))
    return result


if __name__ == "__main__":
    run()
