"""Counter-verified steady-state gate: a short CompiledTrainStep run must
reach a zero-python-overhead steady state, proven by the process-global
``paddle_tpu.profiler.counters`` registry rather than by timing.

Protocol: 2 warmup steps (step 1 hydrates + traces, step 2 retraces once —
the optimizer accumulators change the carried-state structure), then 2
measured steps which must show:

  * 0 retraces           (jit.traces — the python step body never re-runs)
  * 0 rehydrations       (jit.hydrates)
  * 0 host bind/sync work (jit.host.*, jit.syncs)
  * 2 cache hits, 0 misses (every dispatch is a pure jit-cache hit)

A second phase gates the fused multi-step dispatch path
(``fused_steps=K``): after its warmup (window 1 = priming single-step
fallback, window 2 = scan compile), every measured K-step window must be
exactly ONE XLA dispatch — ``jit.host.dispatches == jit.steps / K`` —
again with zero retraces / rehydrates / host binds.

Prints one JSON line; raises AssertionError on any violation.  Wired as a
tier-1 test via tests/test_profiler.py.  Run directly:
``python scripts/check_counters.py``.
"""

import json
import os

WARMUP = 2
MEASURE = 2
FUSED_K = 2
FUSED_MEASURE = 2  # measured windows = FUSED_MEASURE * FUSED_K steps


def run():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import paddle_tpu as paddle
    import paddle_tpu.jit as pjit
    import paddle_tpu.nn as nn
    from paddle_tpu.profiler import counters

    paddle.seed(0)
    model = nn.Sequential(nn.Linear(16, 32), nn.GELU(), nn.Linear(32, 4))
    opt = paddle.optimizer.AdamW(1e-3, parameters=model.parameters())
    x = paddle.randn([8, 16])
    y = paddle.randn([8, 4])

    def loss_fn(m, a, b):
        return ((m(a) - b) ** 2).mean()

    step = pjit.CompiledTrainStep(model, loss_fn, opt)
    for _ in range(WARMUP):
        step(x, y).numpy()
    before = counters.snapshot()
    for _ in range(MEASURE):
        step(x, y).numpy()
    steady = counters.delta(before)

    invariants = {
        "jit.traces": 0,
        "jit.hydrates": 0,
        "jit.syncs": 0,
        "jit.cache_misses": 0,
        "jit.cache_hits": MEASURE,
        "jit.steps": MEASURE,
        "jit.host.dispatches": MEASURE,  # single-step mode: 1 launch/step
    }
    invariants.update({"jit.host." + k: 0 for k in pjit._HOST_SYNC_KEYS})

    violations = {k: (steady.get(k, 0), want)
                  for k, want in invariants.items()
                  if steady.get(k, 0) != want}

    # ---- fused multi-step dispatch gate: dispatches == steps / K --------
    from paddle_tpu.io import Window

    paddle.seed(0)
    fmodel = nn.Sequential(nn.Linear(16, 32), nn.GELU(), nn.Linear(32, 4))
    fopt = paddle.optimizer.AdamW(1e-3, parameters=fmodel.parameters())
    fstep = pjit.CompiledTrainStep(fmodel, loss_fn, fopt,
                                   fused_steps=FUSED_K)
    import numpy as np
    rng = np.random.RandomState(0)
    def window():
        return Window(
            (paddle.to_tensor(rng.randn(FUSED_K, 8, 16).astype("float32")),
             paddle.to_tensor(rng.randn(FUSED_K, 8, 4).astype("float32"))),
            FUSED_K)
    fstep(window()).numpy()  # window 1: priming single-step fallback
    fstep(window()).numpy()  # window 2: scan compile
    fbefore = counters.snapshot()
    for _ in range(FUSED_MEASURE):
        fstep(window()).numpy()
    fsteady = counters.delta(fbefore)

    finvariants = {
        "jit.traces": 0,
        "jit.hydrates": 0,
        "jit.syncs": 0,
        "jit.cache_misses": 0,
        "jit.cache_hits": FUSED_MEASURE,
        "jit.steps": FUSED_MEASURE * FUSED_K,
        "jit.fused_windows": FUSED_MEASURE,
        "jit.fused_fallback_steps": 0,
        # THE fused-dispatch economics gate: one launch per K-step window
        "jit.host.dispatches": (FUSED_MEASURE * FUSED_K) // FUSED_K,
    }
    finvariants.update({"jit.host." + k: 0 for k in pjit._HOST_SYNC_KEYS})
    violations.update({f"fused:{k}": (fsteady.get(k, 0), want)
                       for k, want in finvariants.items()
                       if fsteady.get(k, 0) != want})

    result = {"metric": "steady_state_counter_violations",
              "value": len(violations),
              "unit": f"violations/{MEASURE} steps "
                      f"+ {FUSED_MEASURE} fused windows",
              "violations": {k: {"got": got, "want": want}
                             for k, (got, want) in violations.items()},
              "steady_delta": steady,
              "fused_steady_delta": fsteady}
    print(json.dumps(result))
    if violations:
        raise AssertionError(
            "steady-state counter invariants violated (got != want): "
            + ", ".join(f"{k}: {got} != {want}"
                        for k, (got, want) in sorted(violations.items())))
    return result


if __name__ == "__main__":
    run()
