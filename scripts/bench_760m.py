"""Batch/remat sweep for the GPT-760M MFU leg (perf round 5).

Reuses bench.py's measurement protocol (_run_leg) so sweep numbers stay
comparable to the tracked bench.  Results: scripts/PERF_NOTES.md.

Usage: python scripts/bench_760m.py [batch] [recompute]
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 8
    rec = sys.argv[2] if len(sys.argv) > 2 else "selective_lean"
    if rec == "none":
        rec = False
    from bench import _run_leg
    from paddle_tpu.models import GPTConfig

    cfg = GPTConfig.gpt3_760m(vocab_size=50304, max_seq_len=1024,
                              dtype="bfloat16", use_flash_attention=True,
                              recompute=rec)
    t0 = time.perf_counter()
    tps, spread, n_params = _run_leg(cfg, batch, 1024, 10, 1)
    mfu = tps * 6 * n_params / 197e12
    print(f"batch={batch} rec={rec} params={n_params/1e6:.0f}M "
          f"tok/s={tps:.0f} MFU={mfu:.4f} "
          f"(total {time.perf_counter()-t0:.0f}s)")


if __name__ == "__main__":
    main()
