"""Pretty-print flight-recorder postmortem bundles.

Usage::

    python scripts/flight_dump.py <bundle.json> [...]
    python scripts/flight_dump.py <flight-dir>       # newest bundle
    python scripts/flight_dump.py                    # newest in the
                                                     # default dump dir

Renders the bundle sections written by ``paddle_tpu.profiler.flight.dump``
— reason/context header, active span stack, the health plane's alert set
and last window (when FLAGS_health was on at dump time), the device-time
ledger top-K (program share / mean / p95 / MFU / roofline, when
FLAGS_device_time_sample captured anything), the counters
that MOVED since startup (full snapshot stays in the JSON), histogram
percentiles, and the event ring tail with relative timestamps.  ``--events N`` bounds the tail
(default 40; 0 = all); ``--raw`` re-emits the bundle as indented JSON.
"""

import argparse
import glob
import json
import os
import sys


def _find_bundles(target):
    if os.path.isfile(target):
        return [target]
    if os.path.isdir(target):
        found = sorted(glob.glob(os.path.join(target, "flight-*.json")),
                       key=os.path.getmtime)
        if not found:
            raise SystemExit(f"no flight-*.json bundles under {target}")
        return [found[-1]]
    raise SystemExit(f"{target}: not a bundle file or directory")


def _default_dir():
    # mirror flight.dump_dir() without importing jax transitively
    import tempfile
    return os.path.join(tempfile.gettempdir(), f"ptpu-flight-{os.getpid()}")


def _fmt_val(v):
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


def _render_span_tree(t, w):
    """Indented nested rendering of one request trace embedded in a
    serving-fault bundle (``trace.TraceContext.to_dict()`` shape)."""
    stages = t.get("stage_ns") or {}
    stage_txt = " ".join(f"{k}={v / 1e6:.2f}ms"
                         for k, v in stages.items() if v)
    w(f"  trace {t.get('trace_id')} rid={t.get('rid')} "
      f"status={t.get('status')} keep={t.get('keep_reason')}"
      + (f"  [{stage_txt}]" if stage_txt else "") + "\n")

    def walk(node, depth):
        dur = node.get("dur_ns", 0) / 1e6
        extra = node.get("extra") or {}
        detail = " ".join(f"{k}={_fmt_val(v)}" for k, v in extra.items())
        w(f"  {'  ' * depth}{node.get('name'):<{max(1, 34 - 2 * depth)}}"
          f"{dur:>10.3f}ms" + (f"  {detail}" if detail else "") + "\n")
        for c in node.get("children", []):
            walk(c, depth + 1)

    root = t.get("tree")
    if root:
        walk(root, 1)


def render(path, max_events=40, raw=False, out=sys.stdout):
    with open(path) as f:
        bundle = json.load(f)
    if raw:
        json.dump(bundle, out, indent=2)
        out.write("\n")
        return bundle

    w = out.write
    w(f"== flight bundle {path}\n")
    w(f"reason   : {bundle.get('reason')}\n")
    w(f"pid      : {bundle.get('pid')}   ts: {bundle.get('ts')}\n")
    ctx = dict(bundle.get("context") or {})
    span_trees = ctx.pop("span_trees", None)
    if ctx:
        w("context  :\n")
        for k in sorted(ctx):
            w(f"  {k:<18} {_fmt_val(ctx[k])}\n")
    spans = bundle.get("spans") or []
    w(f"spans    : {' > '.join(spans) if spans else '(none active)'}\n")
    if span_trees:
        w(f"\n-- request span trees ({len(span_trees)}):\n")
        for t in span_trees:
            _render_span_tree(t, w)

    health = bundle.get("health")
    if health:
        alerts = health.get("alerts") or []
        w(f"\n-- alerts (admission={health.get('admission_level')}, "
          f"{sum(1 for a in alerts if a.get('state') == 'firing')} "
          f"firing of {len(alerts)}):\n")
        for a in alerts:
            detail = " ".join(f"{k}={_fmt_val(v)}"
                              for k, v in (a.get("detail") or {}).items())
            w(f"  [{a.get('state'):<8}] {a.get('name'):<20} "
              f"{a.get('kind')}/{a.get('severity')}"
              + (f"  {detail}" if detail else "") + "\n")
        win = health.get("window")
        if win:
            w(f"  window   : {win.get('seconds', 0):.3f}s "
              f"(ticks {win.get('start_tick')}..{win.get('end_tick')})\n")
            for k in sorted(win.get("delta") or {}):
                w(f"    {k:<40} +{_fmt_val(win['delta'][k])}\n")
            for k in sorted(win.get("p95") or {}):
                w(f"    {k:<40} p95 {_fmt_val(win['p95'][k])}\n")

    dt = bundle.get("devicetime")
    if dt and dt.get("programs"):
        progs = dt["programs"]
        w(f"\n-- device time (sample_every={dt.get('sample_every')}, "
          f"est_total={dt.get('est_total_s', 0):.3f}s, "
          f"top {len(progs)}):\n")
        w(f"  {'program':<42}{'share':>7}{'mean':>10}{'p95':>10}"
          f"{'mfu':>7}{'bound':>17}\n")
        for p in progs:
            share = p.get("share")
            mean = p.get("mean_ms")
            p95 = p.get("p95_ms")
            mfu = p.get("mfu")
            w(f"  {p.get('name', '?'):<42}"
              f"{(f'{share:.1%}' if share is not None else '-'):>7}"
              f"{(f'{mean:.3f}ms' if mean is not None else '-'):>10}"
              f"{(f'{p95:.3f}ms' if p95 is not None else '-'):>10}"
              f"{(f'{mfu:.1%}' if mfu is not None else '-'):>7}"
              f"{(p.get('roofline') or '-'):>17}\n")

    moved = {k: v for k, v in (bundle.get("counters_delta") or {}).items()
             if v}
    if moved:
        w(f"\n-- counters moved since startup ({len(moved)}):\n")
        for k in sorted(moved):
            w(f"  {k:<42} {_fmt_val(moved[k])}\n")

    hists = bundle.get("histograms") or {}
    live = {k: s for k, s in hists.items() if s.get("count")}
    if live:
        w(f"\n-- histograms ({len(live)}):\n")
        w(f"  {'name':<28}{'count':>8}{'mean':>12}{'p50':>12}"
          f"{'p95':>12}{'p99':>12}{'max':>12}\n")
        for k in sorted(live):
            s = live[k]
            w(f"  {k:<28}{s['count']:>8}"
              + "".join(f"{_fmt_val(s[f]):>12}"
                        for f in ("mean", "p50", "p95", "p99", "max"))
              + "\n")

    events = bundle.get("events") or []
    shown = events if not max_events else events[-max_events:]
    w(f"\n-- events (last {len(shown)} of {len(events)}):\n")
    t_end = events[-1]["ts_ns"] if events else 0
    for ev in shown:
        rel_ms = (ev["ts_ns"] - t_end) / 1e6
        fields = {k: v for k, v in ev.items() if k not in ("ts_ns", "kind")}
        detail = " ".join(f"{k}={_fmt_val(v)}" for k, v in fields.items())
        w(f"  {rel_ms:>10.1f}ms  {ev['kind']:<20} {detail}\n")
    return bundle


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("paths", nargs="*",
                    help="bundle file(s) or a flight dump directory "
                         "(default: this process's default dump dir)")
    ap.add_argument("--events", type=int, default=40,
                    help="event-tail length to show (0 = all)")
    ap.add_argument("--raw", action="store_true",
                    help="re-emit the bundle as indented JSON")
    args = ap.parse_args(argv)
    targets = args.paths or [_default_dir()]
    bundles = [b for t in targets for b in _find_bundles(t)]
    for i, b in enumerate(bundles):
        if i:
            sys.stdout.write("\n")
        render(b, max_events=args.events, raw=args.raw)


if __name__ == "__main__":
    main()
