#!/usr/bin/env bash
# CI gate, in dependency order: TPU-hazard lint (fails on findings not in
# the baseline), perf-trajectory regression check over the committed
# BENCH_r0*.json history, then the steady-state counter invariants —
# including the disagg phase (block-granular migration economics: copied
# == owned non-shared blocks, prefix blocks never moved twice, zero
# retraces across the prefill/decode split, token identity vs unified)
# and the tiering phase (host-RAM KV tier under an oversubscribed pool:
# spill/restore token identity for greedy AND seeded sampling, zero
# steady-state retraces/syncs, flat host arena once the buffer reuse
# pool is warm, and kv_spill_drop chaos degrading to a cache miss),
# and the devicetime phase (sample=0 byte-identical OFF parity;
# sample=4 pays exactly ceil(dispatches/4) fences with token identity
# and a ledger whose MFU/roofline gauges survive GET /programs and
# bench_compare --attribute), and the mesh-serving phase (mp2 paged
# decode over the StateArena: token identity vs single-device, zero
# steady retraces/hydrates/host-syncs with dispatch counts unchanged,
# the KV pool genuinely head-sharded per chip, and the audit census
# proving in-graph collectives only — zero host launches), and the
# adapters phase (multi-tenant LoRA serving: a heterogeneous batch of
# three tenants + base rows token-identical to per-tenant sequential
# through ONE compiled decode program, base rows bitwise passthrough,
# zero steady retraces/loads with dispatch counts equal to the
# adapter-free twin, and eviction-then-reuse paging tenants back in
# warm — loads move, programs never retrace).
#
# Usage: scripts/ci_gate.sh        (from anywhere; cd's to the repo root)
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== ci_gate: TPU-hazard lint (PT001-PT006, baseline-checked) =="
python scripts/lint_tpu.py --check

echo "== ci_gate: bench perf-trajectory regression =="
# rc 2 means not enough parseable history (fresh clone / bootstrap run):
# nothing to compare against is not a regression.
rc=0
python scripts/bench_compare.py --glob 'BENCH_r0*.json' || rc=$?
if [ "$rc" -eq 1 ]; then
    exit 1
elif [ "$rc" -eq 2 ]; then
    echo "(not enough bench history yet -- comparison skipped)"
elif [ "$rc" -ne 0 ]; then
    exit "$rc"
fi

echo "== ci_gate: steady-state counter invariants (incl. disagg, tiering, devicetime, mesh-serving, adapters) =="
JAX_PLATFORMS="${JAX_PLATFORMS:-cpu}" PYTHONPATH=. \
    python scripts/check_counters.py

echo "ci_gate: OK"
