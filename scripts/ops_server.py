"""Stand up the live ops endpoint (``paddle_tpu.profiler.ops.OpsServer``).

Usage::

    python scripts/ops_server.py --port 8321            # bare process plane
    python scripts/ops_server.py --demo                 # + tiny fleet traffic
    python scripts/ops_server.py --demo --trace-sample 1.0 --duration 30

Serves on ``127.0.0.1``:

  /healthz  /metrics  /goodput  /traces  /traces/<trace_id>  /flight

With ``--demo`` a tiny 2-replica ``ServingFleet`` over a toy GPT runs
request traffic in the background (request tracing on at
``--trace-sample``), so every endpoint has live data to show; without it
the endpoints expose whatever the process has recorded (counters and the
flight ring are always live).  Runs for ``--duration`` seconds (0 = until
Ctrl-C), then prints each endpoint's status line and exits.
"""

import argparse
import json
import os
import sys
import threading
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _demo_fleet(trace_sample):
    """A tiny fleet + a background submitter thread; returns (fleet, stop)."""
    import numpy as np

    from paddle_tpu.core import flags
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import RetryAfter, ServingFleet

    flags.set_flags({"FLAGS_request_trace_sample": float(trace_sample)})
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=64)
    fleet = ServingFleet(GPTForCausalLM(cfg), replicas=2, max_slots=4,
                         min_bucket=4, threaded=True, warm_buckets=(8,))
    stop = threading.Event()

    def _traffic():
        rng = np.random.RandomState(0)
        while not stop.is_set():
            prompt = rng.randint(1, 64, size=rng.randint(4, 12)).astype("int32")
            try:
                fleet.submit(prompt, max_new_tokens=8,
                             seed=int(rng.randint(2**31)))
            except RetryAfter:
                pass
            stop.wait(0.05)

    threading.Thread(target=_traffic, name="ops-demo-traffic",
                     daemon=True).start()
    return fleet, stop


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--port", type=int, default=8321,
                    help="bind port (0 = ephemeral; printed at startup)")
    ap.add_argument("--demo", action="store_true",
                    help="run a tiny traced serving fleet so the endpoints "
                         "have live data")
    ap.add_argument("--trace-sample", type=float, default=1.0,
                    help="FLAGS_request_trace_sample for --demo traffic")
    ap.add_argument("--duration", type=float, default=0.0,
                    help="seconds to serve (0 = until Ctrl-C)")
    args = ap.parse_args(argv)

    from paddle_tpu.profiler.ops import OpsServer

    fleet = stop = None
    if args.demo:
        fleet, stop = _demo_fleet(args.trace_sample)
    srv = OpsServer(fleet=fleet, port=args.port)
    port = srv.start()
    print(f"ops endpoint live at http://127.0.0.1:{port}  "
          "(/healthz /metrics /goodput /traces /flight)")
    try:
        if args.duration > 0:
            time.sleep(args.duration)
        else:
            while True:
                time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        if stop is not None:
            stop.set()
        for path in ("/healthz", "/metrics", "/goodput", "/traces",
                     "/flight"):
            try:
                with urllib.request.urlopen(srv.url(path), timeout=5) as r:
                    body = r.read()
                    line = (body.decode().splitlines() or [""])[0] \
                        if path == "/metrics" else \
                        json.dumps(json.loads(body))[:100]
                    print(f"  {r.status} {path:<10} {line}")
            except urllib.error.HTTPError as e:
                # /goodput is 404 without an attached trainer ledger
                print(f"  {e.code} {path:<10} {e.read().decode()[:100]}")
            except Exception as e:  # noqa: BLE001 — summary must not crash
                print(f"  ERR {path:<10} {e}", file=sys.stderr)
        if fleet is not None:
            fleet.drain()
        srv.stop()


if __name__ == "__main__":
    main()
