"""Perf-contract smoke: 3 steps of a tiny GPT on CPU.

Steps 2-3 (steady state) must do ZERO host-side hydrate/bind work — the
device-resident contract of jit.CompiledTrainStep, watched through the
process-global ``paddle_tpu.profiler.counters`` registry (jit.host.* keys;
``jit.host_sync_counts()`` is now a view over the same counters).  Step 3
must additionally be a pure cache hit: zero retraces (``jit.traces``).
Prints one JSON line; raises on violation.

Run directly (``python scripts/bench_smoke.py``), via ``PTPU_BENCH_SMOKE=1
python bench.py``, or through tests/test_train_step_state.py (tier-1).
"""

import json
import os


def run():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.jit as pjit
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainingCriterion)
    from paddle_tpu.profiler import counters

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
                    max_seq_len=64, use_flash_attention=False)
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion()
    opt = paddle.optimizer.AdamW(1e-4, parameters=model.parameters())
    ids = paddle.randint(0, cfg.vocab_size, [2, 64])
    labels = paddle.randint(0, cfg.vocab_size, [2, 64])

    def loss_fn(m, x, l):
        return crit(m(x), l)

    step = pjit.CompiledTrainStep(model, loss_fn, opt)
    losses = [float(step(ids, labels).numpy())]  # step 1: hydrate + compile
    before = counters.snapshot()
    losses.append(float(step(ids, labels).numpy()))  # step 2 (retrace only)
    mid = counters.snapshot()
    losses.append(float(step(ids, labels).numpy()))  # step 3 (cached)
    after = counters.snapshot()

    host_keys = ["jit.host." + k for k in pjit._HOST_SYNC_KEYS]
    host_keys += ["jit.hydrates", "jit.syncs"]
    steady = counters.delta(before, after)
    host_delta = {k: steady.get(k, 0) for k in host_keys}
    step3 = counters.delta(mid, after)

    result = {"metric": "steady_state_host_syncs",
              "value": sum(host_delta.values()),
              "unit": "calls/2 steps",
              "delta": host_delta,
              "step3_retraces": step3.get("jit.traces", 0),
              "counters": {k: v for k, v in steady.items()
                           if k.startswith(("jit.", "io.", "dist.",
                                            "optimizer."))},
              "losses": [round(l, 6) for l in losses]}
    print(json.dumps(result))
    if sum(host_delta.values()) != 0:
        raise AssertionError(
            f"steady-state steps did host hydrate/bind work: {host_delta}")
    if result["step3_retraces"] != 0:
        raise AssertionError(
            f"step 3 retraced: jit.traces += {result['step3_retraces']} "
            "(expected a pure jit cache hit after the step-2 "
            "accumulator-structure retrace)")
    if not all(np.isfinite(l) for l in losses):
        raise AssertionError(f"non-finite loss in smoke run: {losses}")
    return result


if __name__ == "__main__":
    run()
