"""Perf-contract smoke: 3 steps of a tiny GPT on CPU.

Steps 2-3 (steady state) must do ZERO host-side hydrate/bind work — the
device-resident contract of jit.CompiledTrainStep, watched through the
process-global ``paddle_tpu.profiler.counters`` registry (jit.host.* keys;
``jit.host_sync_counts()`` is now a view over the same counters).  Step 3
must additionally be a pure cache hit: zero retraces (``jit.traces``).
Prints one JSON line; raises on violation.

A fused-dispatch phase re-runs the same model with ``fused_steps=K`` and
gates the launch economics: a steady K-step window must be exactly ONE
XLA dispatch (``jit.host.dispatches == jit.steps / K``) with zero
retraces.

A checkpointed-run phase gates the resilience contract: async
``resilience.CheckpointManager`` saves interleaved with fused windows
must cost exactly ONE counter-gated ``jit.syncs`` (+ its
``bind_layer_state``/``bind_optimizer_state`` pair) per save and nothing
else — zero retraces, zero rehydrates, zero ``layer_state``/
``optimizer_state`` host reads; the disk write overlaps the next window
on a background thread.

A flight-recorder phase injects a ``nan_loss`` fault into a tiny
``FaultTolerantTrainer`` run and gates the postmortem contract: recovery
must leave exactly one flight dump (reason ``trainer_recover``) whose
context names the ``NonFiniteLossError``, while the run itself still
finishes with finite losses.

A goodput phase runs a tiny ``FaultTolerantTrainer`` twice — clean, and
under an injected preemption — and gates the wall-clock ledger
(``profiler.goodput``): in both runs >=99% of wall time must land in a
named bucket, and the preempted run must actually fill the ``recovery``
and ``restore_replay`` badput buckets.

A serving phase runs mixed-length staggered requests through
``serving.LLMEngine`` and asserts the outputs are TOKEN-IDENTICAL to
sequential per-request ``GPT.generate``; it reports decode tokens/s for
both paths (the speedup is informational on CPU — the batching win is a
TPU property).

A mesh phase (on >=2 devices — forced host devices under
``XLA_FLAGS=--xla_force_host_platform_device_count=8``) re-runs the same
fused GPT mesh-native on a dp=2 mesh (``CompiledTrainStep(mesh=...)``,
batch staged with data-parallel ``NamedSharding``) and gates the
multi-chip economics: a steady fused window is still exactly ONE XLA
dispatch with zero retraces, and the losses match the single-device
fused run (GSPMD gradient averaging is numerically invisible).

Run directly (``python scripts/bench_smoke.py``), via ``PTPU_BENCH_SMOKE=1
python bench.py``, or through tests/test_train_step_state.py (tier-1).
"""

import json
import os


def run():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    # the mesh phase needs >1 device; only effective before the first jax
    # import, no-op on real TPUs
    if ("--xla_force_host_platform_device_count"
            not in os.environ.get("XLA_FLAGS", "")):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8").strip()
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.jit as pjit
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainingCriterion)
    from paddle_tpu.profiler import counters

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
                    max_seq_len=64, use_flash_attention=False)
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion()
    opt = paddle.optimizer.AdamW(1e-4, parameters=model.parameters())
    ids = paddle.randint(0, cfg.vocab_size, [2, 64])
    labels = paddle.randint(0, cfg.vocab_size, [2, 64])

    def loss_fn(m, x, l):
        return crit(m(x), l)

    step = pjit.CompiledTrainStep(model, loss_fn, opt)
    losses = [float(step(ids, labels).numpy())]  # step 1: hydrate + compile
    before = counters.snapshot()
    losses.append(float(step(ids, labels).numpy()))  # step 2 (retrace only)
    mid = counters.snapshot()
    losses.append(float(step(ids, labels).numpy()))  # step 3 (cached)
    after = counters.snapshot()

    host_keys = ["jit.host." + k for k in pjit._HOST_SYNC_KEYS]
    host_keys += ["jit.hydrates", "jit.syncs"]
    steady = counters.delta(before, after)
    host_delta = {k: steady.get(k, 0) for k in host_keys}
    step3 = counters.delta(mid, after)

    # ---- fused multi-step dispatch: one launch per K-step window --------
    from paddle_tpu.io import Window
    fused_k = 2
    paddle.seed(0)
    fmodel = GPTForCausalLM(cfg)
    fopt = paddle.optimizer.AdamW(1e-4, parameters=fmodel.parameters())
    fstep = pjit.CompiledTrainStep(fmodel, loss_fn, fopt,
                                   fused_steps=fused_k)
    wids = paddle.to_tensor(np.stack([np.asarray(ids.numpy())] * fused_k))
    wlabels = paddle.to_tensor(np.stack([np.asarray(labels.numpy())]
                                        * fused_k))
    win = Window((wids, wlabels), fused_k)
    fstep(win).numpy()   # window 1: priming single-step fallback
    fstep(win).numpy()   # window 2: scan compile
    fbefore = counters.snapshot()
    flosses = [round(float(l), 6)
               for l in np.asarray(fstep(win).numpy())]  # steady window
    fused = counters.delta(fbefore)
    fused_dispatches = fused.get("jit.host.dispatches", 0)
    fused_steps_done = fused.get("jit.steps", 0)

    # ---- resilience: async checkpoints overlap the next fused window ----
    import tempfile
    import time
    from paddle_tpu.resilience import CheckpointManager

    ckpt_saves = 2
    with tempfile.TemporaryDirectory() as ckdir:
        mgr = CheckpointManager(ckdir, keep_last=2, async_save=True)
        rbefore = counters.snapshot()
        t0 = time.perf_counter()
        for i in range(ckpt_saves):
            # snapshot (one sync + D2H copies) on this thread, disk write
            # on a daemon thread — the next fused window overlaps it
            mgr.save(fstep, (i + 1) * fused_k, blocking=False)
            fstep(win).numpy()
        mgr.wait()
        ckpt_wall_s = time.perf_counter() - t0
        rdelta = counters.delta(rbefore)
    ckpt_host_delta = {k: rdelta.get(k, 0) for k in host_keys}
    # budget: exactly one counter-gated sync (one bind pair) per save
    ckpt_extra_syncs = (
        sum(ckpt_host_delta.values())
        - rdelta.get("jit.syncs", 0)
        - rdelta.get("jit.host.bind_layer_state", 0)
        - rdelta.get("jit.host.bind_optimizer_state", 0))

    # ---- flight recorder: an injected NaN fault must leave a postmortem -
    import paddle_tpu.nn as nn
    from paddle_tpu.io import DataLoader, TensorDataset
    from paddle_tpu.profiler import flight
    from paddle_tpu.resilience import (CheckpointManager as _CkptMgr,
                                       FaultTolerantTrainer, faultinject)

    def _mse(m, x, y):
        return ((m(x) - y) ** 2).mean()

    paddle.seed(0)
    fnet = nn.Sequential(nn.Linear(6, 12), nn.GELU(), nn.Linear(12, 3))
    fr_opt = paddle.optimizer.AdamW(5e-2, parameters=fnet.parameters())
    fr_step = pjit.CompiledTrainStep(fnet, _mse, fr_opt)
    frng = np.random.RandomState(3)
    fr_ds = TensorDataset(
        [paddle.to_tensor(frng.randn(32, 6).astype("float32")),
         paddle.to_tensor(frng.randn(32, 3).astype("float32"))])
    with tempfile.TemporaryDirectory() as fdir:
        flight.configure(directory=fdir)
        flight.clear()
        with faultinject.fault_schedule("nan_loss@3"):
            trainer = FaultTolerantTrainer(
                fr_step, lambda epoch: DataLoader(fr_ds, batch_size=4,
                                                  shuffle=False),
                _CkptMgr(os.path.join(fdir, "ckpt"), keep_last=2),
                epochs=1, max_steps=6, save_every=2)
            fr_losses = trainer.run()
        fr_dump_path = flight.last_dump_path()
        fr_bundle = flight.load(fr_dump_path) if fr_dump_path else {}
        flight.configure(directory="")
    flight_phase = {
        "flight_nan_recoveries": trainer.recoveries,
        "flight_dump_reason": fr_bundle.get("reason"),
        "flight_dump_error": (fr_bundle.get("context") or {}).get("error"),
        "flight_dump_events": len(fr_bundle.get("events", [])),
    }

    # ---- goodput ledger: >=99% of trainer wall time lands in a named
    # bucket, on a clean run AND under an injected preemption (where the
    # recovery / restore_replay buckets must actually fill) -------------
    def _goodput_run(schedule=None):
        paddle.seed(0)
        gnet = nn.Sequential(nn.Linear(6, 12), nn.GELU(), nn.Linear(12, 3))
        g_opt = paddle.optimizer.AdamW(5e-2, parameters=gnet.parameters())
        g_step = pjit.CompiledTrainStep(gnet, _mse, g_opt)
        with tempfile.TemporaryDirectory() as gdir:
            gtrainer = FaultTolerantTrainer(
                g_step, lambda epoch: DataLoader(fr_ds, batch_size=4,
                                                 shuffle=False),
                _CkptMgr(os.path.join(gdir, "ckpt"), keep_last=2),
                epochs=1, max_steps=6, save_every=2)
            if schedule:
                with faultinject.fault_schedule(schedule):
                    gtrainer.run()
            else:
                gtrainer.run()
        return gtrainer.goodput.report()

    g_clean = _goodput_run()
    g_fault = _goodput_run("preempt@3")
    goodput_phase = {
        "goodput_clean_accounted": round(g_clean["accounted"], 4),
        "goodput_clean_fraction": round(g_clean["goodput"], 4),
        "goodput_fault_accounted": round(g_fault["accounted"], 4),
        "goodput_fault_fraction": round(g_fault["goodput"], 4),
        "goodput_fault_recovery_s":
            round(g_fault["buckets_s"].get("recovery", 0.0), 4),
        "goodput_fault_restore_s":
            round(g_fault["buckets_s"].get("restore_replay", 0.0), 4),
    }

    # ---- serving: engine output must match sequential generate ----------
    from paddle_tpu.serving import LLMEngine

    paddle.seed(0)
    smodel = GPTForCausalLM(cfg)
    smodel.eval()
    rng = np.random.RandomState(11)
    max_new = 8
    prompts = [rng.randint(0, cfg.vocab_size, size=n).tolist()
               for n in (5, 9, 3, 12, 7, 6, 10, 4)]

    # sequential baseline: one generate call per request (warm pass first
    # so both paths are timed compiled)
    def seq_pass():
        return [np.asarray(smodel.generate(
            paddle.to_tensor(np.asarray([p])),
            max_new_tokens=max_new).numpy())[0] for p in prompts]
    seq_pass()
    t0 = time.perf_counter()
    seq_outs = seq_pass()
    seq_s = time.perf_counter() - t0

    eng = LLMEngine(smodel, max_slots=4, max_seq_len=cfg.max_seq_len,
                    min_bucket=4)
    # warm the engine's bucket/decode programs on the same length mix
    for o in eng.generate(prompts, max_new_tokens=max_new):
        pass
    sbefore = counters.snapshot()
    t0 = time.perf_counter()
    eng_outs = eng.generate(prompts, max_new_tokens=max_new)
    serve_s = time.perf_counter() - t0
    sdelta = counters.delta(sbefore)

    outputs_match = all(np.array_equal(e, s)
                        for e, s in zip(eng_outs, seq_outs))
    decode_tokens = len(prompts) * max_new
    serve_tps = decode_tokens / max(serve_s, 1e-9)
    seq_tps = decode_tokens / max(seq_s, 1e-9)

    # ---- paged KV: same prompts, same tokens, zero steady retraces ------
    peng = LLMEngine(smodel, max_slots=4, max_seq_len=cfg.max_seq_len,
                     min_bucket=4, kv_layout="paged", block_size=4,
                     prefill_chunk=8)
    # two warm passes: the first compiles the chunk/decode programs, the
    # second re-serves the (now prefix-cached) prompts so the timed pass
    # runs the same prefix-hit chunk pattern against warm programs
    for _ in range(2):
        for o in peng.generate(prompts, max_new_tokens=max_new):
            pass
    pbefore = counters.snapshot()
    t0 = time.perf_counter()
    paged_outs = peng.generate(prompts, max_new_tokens=max_new)
    paged_s = time.perf_counter() - t0
    pdelta = counters.delta(pbefore)
    paged_match = all(np.array_equal(e, s)
                      for e, s in zip(paged_outs, seq_outs))
    paged_tps = decode_tokens / max(paged_s, 1e-9)
    # shared-prefix leg: one system prompt, distinct tails, served
    # sequentially so every finish feeds the prefix tree
    sysp = rng.randint(0, cfg.vocab_size, size=12).tolist()
    phbefore = counters.snapshot()
    for _ in range(3):
        tail = rng.randint(0, cfg.vocab_size, size=3).tolist()
        for o in peng.generate([sysp + tail], max_new_tokens=4):
            pass
    phdelta = counters.delta(phbefore)

    # ---- mesh: fused dp=2 SPMD keeps the launch economics + the loss ----
    import jax
    if jax.device_count() >= 2:
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        mesh = Mesh(np.array(jax.devices()[:2]).reshape(2, 1),
                    ("dp", "mp"))
        paddle.seed(0)
        mmodel = GPTForCausalLM(cfg)
        mopt = paddle.optimizer.AdamW(1e-4,
                                      parameters=mmodel.parameters())
        mstep = pjit.CompiledTrainStep(mmodel, loss_fn, mopt,
                                       fused_steps=fused_k, mesh=mesh)
        # stage the window with its data-parallel sharding up front, the
        # way the sharded prefetchers do (batch axis is dim 1 of a window)
        wsh = NamedSharding(mesh, P(None, *mstep._batch_spec))
        mwin = Window(
            tuple(paddle.Tensor(jax.device_put(t._data, wsh))
                  for t in (wids, wlabels)), fused_k)
        mstep(mwin).numpy()   # window 1: priming single-step fallback
        mstep(mwin).numpy()   # window 2: scan compile
        mbefore = counters.snapshot()
        mlosses = [round(float(l), 6)
                   for l in np.asarray(mstep(mwin).numpy())]
        mdelta = counters.delta(mbefore)
        mesh_phase = {
            "mesh_devices": 2,
            "mesh_window_dispatches": mdelta.get("jit.host.dispatches",
                                                 0),
            "mesh_window_steps": mdelta.get("jit.steps", 0),
            "mesh_window_retraces": mdelta.get("jit.traces", 0),
            "mesh_window_rehydrates": mdelta.get("jit.hydrates", 0),
            "mesh_sharded_put_bytes": counters.get(
                "dist.device_put_sharded_bytes", 0),
            "mesh_losses": mlosses,
            "mesh_losses_match": bool(np.allclose(mlosses, flosses,
                                                  rtol=1e-4, atol=1e-5)),
        }
    else:
        mesh_phase = {"mesh_devices": jax.device_count(),
                      "mesh_skipped": "needs 2 devices"}

    result = {"metric": "steady_state_host_syncs",
              "value": sum(host_delta.values()),
              "unit": "calls/2 steps",
              "delta": host_delta,
              "step3_retraces": step3.get("jit.traces", 0),
              "steady_dispatches": steady.get("jit.host.dispatches", 0),
              "counters": {k: v for k, v in steady.items()
                           if k.startswith(("jit.", "io.", "dist.",
                                            "optimizer."))},
              "losses": [round(l, 6) for l in losses],
              "fused_k": fused_k,
              "fused_window_dispatches": fused_dispatches,
              "fused_window_steps": fused_steps_done,
              "fused_window_retraces": fused.get("jit.traces", 0),
              "fused_losses": flosses,
              "ckpt_async_saves": rdelta.get("resilience.saves", 0),
              "ckpt_save_ms": rdelta.get("resilience.save_ms", 0),
              "ckpt_wall_s": round(ckpt_wall_s, 4),
              "ckpt_syncs": rdelta.get("jit.syncs", 0),
              "ckpt_retraces": rdelta.get("jit.traces", 0),
              "ckpt_rehydrates": rdelta.get("jit.hydrates", 0),
              "ckpt_extra_host_syncs": ckpt_extra_syncs,
              "serve_requests": len(prompts),
              "serve_decode_tokens": decode_tokens,
              "serve_decode_tokens_per_sec": round(serve_tps, 1),
              "sequential_decode_tokens_per_sec": round(seq_tps, 1),
              "serve_speedup": round(serve_tps / max(seq_tps, 1e-9), 3),
              "serve_outputs_match_generate": outputs_match,
              "serve_steady_retraces": sdelta.get("serving.retraces", 0),
              "paged_outputs_match_generate": paged_match,
              "paged_steady_retraces": pdelta.get("serving.retraces", 0),
              "paged_decode_tokens_per_sec": round(paged_tps, 1),
              "paged_prefix_hits": phdelta.get("serving.kv.prefix_hits", 0),
              "paged_prefill_chunks": phdelta.get("serving.kv.prefill_chunks",
                                                  0),
              "paged_cow_copies": pdelta.get("serving.kv.cow_copies", 0),
              "serve_prefill_programs": eng.stats()["prefill_programs"]}
    result.update(flight_phase)
    result.update(goodput_phase)
    result.update(mesh_phase)
    print(json.dumps(result))
    if sum(host_delta.values()) != 0:
        raise AssertionError(
            f"steady-state steps did host hydrate/bind work: {host_delta}")
    if result["step3_retraces"] != 0:
        raise AssertionError(
            f"step 3 retraced: jit.traces += {result['step3_retraces']} "
            "(expected a pure jit cache hit after the step-2 "
            "accumulator-structure retrace)")
    if result["steady_dispatches"] != 2:
        raise AssertionError(
            "steady-state single-step mode must be exactly 1 XLA dispatch "
            f"per step: jit.host.dispatches += {result['steady_dispatches']} "
            "over 2 steps")
    if fused_steps_done != fused_k or fused_dispatches != 1:
        raise AssertionError(
            "fused dispatch economics violated: a steady fused window must "
            f"be jit.steps / K == {fused_steps_done} / {fused_k} == 1 XLA "
            f"dispatch, got jit.host.dispatches += {fused_dispatches}")
    if result["fused_window_retraces"] != 0:
        raise AssertionError(
            "steady fused window retraced: jit.traces += "
            f"{result['fused_window_retraces']}")
    if result["ckpt_async_saves"] != ckpt_saves or \
            rdelta.get("resilience.save_failures", 0) != 0:
        raise AssertionError(
            f"checkpointed run: expected {ckpt_saves} clean async saves, "
            f"got {result['ckpt_async_saves']} (failures: "
            f"{rdelta.get('resilience.save_failures', 0)})")
    if result["ckpt_syncs"] != ckpt_saves or result["ckpt_retraces"] != 0 \
            or result["ckpt_rehydrates"] != 0 or ckpt_extra_syncs != 0:
        raise AssertionError(
            "checkpointed run broke the one-sync-per-save budget: "
            f"jit.syncs += {result['ckpt_syncs']} (want {ckpt_saves}), "
            f"retraces {result['ckpt_retraces']}, rehydrates "
            f"{result['ckpt_rehydrates']}, extra host syncs "
            f"{ckpt_extra_syncs}: {ckpt_host_delta}")
    if not all(np.isfinite(l) for l in losses + flosses):
        raise AssertionError(
            f"non-finite loss in smoke run: {losses} / {flosses}")
    if (trainer.recoveries != 1 or fr_dump_path is None
            or fr_bundle.get("reason") != "trainer_recover"
            or "NonFiniteLossError" not in (flight_phase["flight_dump_error"]
                                            or "")
            or not all(np.isfinite(v) for v in fr_losses.values())):
        raise AssertionError(
            "injected NaN fault did not produce a flight-recorder "
            f"postmortem (or the recovery was unclean): {flight_phase}, "
            f"dump={fr_dump_path}")
    if goodput_phase["goodput_clean_accounted"] < 0.99 or \
            goodput_phase["goodput_fault_accounted"] < 0.99:
        raise AssertionError(
            "goodput ledger failed to account >=99% of trainer wall time: "
            f"clean {goodput_phase['goodput_clean_accounted']}, "
            f"faulted {goodput_phase['goodput_fault_accounted']}")
    if goodput_phase["goodput_fault_recovery_s"] <= 0 or \
            goodput_phase["goodput_fault_restore_s"] <= 0:
        raise AssertionError(
            "preempted run left the recovery / restore_replay goodput "
            f"buckets empty: {goodput_phase}")
    if not outputs_match:
        raise AssertionError(
            "serving engine output diverged from sequential GPT.generate "
            "on the same prompts (continuous batching must be invisible "
            "in the tokens)")
    if result["serve_steady_retraces"] != 0:
        raise AssertionError(
            "warm serving pass retraced: serving.retraces += "
            f"{result['serve_steady_retraces']} (bucketed prefill should "
            "reuse every compiled program)")
    if not result["paged_outputs_match_generate"]:
        raise AssertionError(
            "paged engine output diverged from sequential GPT.generate "
            "(block tables, prefix sharing, and chunked prefill must be "
            "invisible in the tokens)")
    if result["paged_steady_retraces"] != 0:
        raise AssertionError(
            "warm paged pass retraced: serving.retraces += "
            f"{result['paged_steady_retraces']} (block tables are "
            "operands; steady state is chunk buckets + one decode + one "
            "COW program)")
    if result["paged_prefix_hits"] < 2:
        raise AssertionError(
            "shared-prefix workload scored "
            f"{result['paged_prefix_hits']} prefix-cache hits (want >= 2)")
    if "mesh_skipped" not in mesh_phase:
        if (mesh_phase["mesh_window_dispatches"] != 1
                or mesh_phase["mesh_window_steps"] != fused_k
                or mesh_phase["mesh_window_retraces"] != 0
                or mesh_phase["mesh_window_rehydrates"] != 0):
            raise AssertionError(
                "mesh fused-dispatch economics violated: a steady dp=2 "
                f"window must be 1 XLA dispatch / {fused_k} steps with "
                f"zero retraces/rehydrates, got {mesh_phase}")
        if not mesh_phase["mesh_losses_match"]:
            raise AssertionError(
                "mesh dp=2 losses diverged from the single-device fused "
                f"run: {mesh_phase['mesh_losses']} vs {flosses}")
        if mesh_phase["mesh_sharded_put_bytes"] <= 0:
            raise AssertionError(
                "mesh phase staged no sharded bytes — "
                "dist.device_put_sharded_bytes never moved")
    return result


if __name__ == "__main__":
    run()
