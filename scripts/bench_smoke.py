"""Perf-contract smoke: 3 steps of a tiny GPT on CPU.

Steps 2-3 (steady state) must do ZERO host-side hydrate/bind work — the
device-resident contract of jit.CompiledTrainStep, watched through the
jit.host_sync_counts() counters.  Prints one JSON line; raises on violation.

Run directly (``python scripts/bench_smoke.py``), via ``PTPU_BENCH_SMOKE=1
python bench.py``, or through tests/test_train_step_state.py (tier-1).
"""

import json
import os


def run():
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.jit as pjit
    from paddle_tpu.models import (GPTConfig, GPTForCausalLM,
                                   GPTPretrainingCriterion)

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
                    max_seq_len=64, use_flash_attention=False)
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion()
    opt = paddle.optimizer.AdamW(1e-4, parameters=model.parameters())
    ids = paddle.randint(0, cfg.vocab_size, [2, 64])
    labels = paddle.randint(0, cfg.vocab_size, [2, 64])

    def loss_fn(m, x, l):
        return crit(m(x), l)

    step = pjit.CompiledTrainStep(model, loss_fn, opt)
    losses = [float(step(ids, labels).numpy())]  # step 1: hydrate + compile
    before = pjit.host_sync_counts()
    losses.append(float(step(ids, labels).numpy()))  # step 2 (retrace only)
    losses.append(float(step(ids, labels).numpy()))  # step 3 (cached)
    after = pjit.host_sync_counts()
    delta = {k: after[k] - before[k] for k in after}

    result = {"metric": "steady_state_host_syncs",
              "value": sum(delta.values()),
              "unit": "calls/2 steps",
              "delta": delta,
              "losses": [round(l, 6) for l in losses]}
    print(json.dumps(result))
    if sum(delta.values()) != 0:
        raise AssertionError(
            f"steady-state steps did host hydrate/bind work: {delta}")
    if not all(np.isfinite(l) for l in losses):
        raise AssertionError(f"non-finite loss in smoke run: {losses}")
    return result


if __name__ == "__main__":
    run()
