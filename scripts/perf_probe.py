"""Perf probe: sweep batch size and loss variants on the real chip."""
import time, json, sys
import numpy as np
import jax, jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.jit import CompiledTrainStep
from paddle_tpu.models import GPTConfig, GPTForCausalLM, GPTPretrainingCriterion


def run(batch, seq, fused_loss, iters=20, recompute=False):
    cfg = GPTConfig.gpt3_125m(vocab_size=50304, max_seq_len=seq,
                              dtype="bfloat16", use_flash_attention=True,
                              recompute=recompute)
    model = GPTForCausalLM(cfg)
    crit = GPTPretrainingCriterion()
    opt = paddle.optimizer.AdamW(1e-4, parameters=model.parameters())
    ids = paddle.randint(0, cfg.vocab_size, [batch, seq])
    labels = paddle.randint(0, cfg.vocab_size, [batch, seq])

    if fused_loss:
        def loss_fn(m, x, l):
            from paddle_tpu.core.dispatch import apply_op
            logits = m(x)
            def fn(lg, lb):
                lg = lg.astype(jnp.float32)
                lse = jax.nn.logsumexp(lg, -1)
                picked = jnp.take_along_axis(
                    lg, lb[..., None].astype(jnp.int32), -1)[..., 0]
                return jnp.mean(lse - picked)
            return apply_op("ce", fn, logits, l)
    else:
        def loss_fn(m, x, l):
            return crit(m(x), l)

    step = CompiledTrainStep(model, loss_fn, opt)
    step(ids, labels); step(ids, labels)
    loss = step(ids, labels); loss.numpy()
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step(ids, labels)
    loss.numpy()
    dt = time.perf_counter() - t0
    tps = batch * seq * iters / dt
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    mfu = tps * 6 * n_params / 197e12
    print(json.dumps({"batch": batch, "seq": seq, "fused": fused_loss,
                      "recompute": recompute,
                      "tok_s": round(tps, 0), "ms_step": round(dt/iters*1e3, 2),
                      "mfu_6N": round(mfu, 4)}), flush=True)


if __name__ == "__main__":
    for b, fused, rc in [(8, True, False), (16, True, True), (32, True, True)]:
        try:
            run(b, 1024, fused, recompute=rc)
        except Exception as e:
            print(json.dumps({"batch": b, "fused": fused, "rc": rc,
                              "error": str(e)[:200]}), flush=True)
